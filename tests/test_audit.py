"""Audit plane (ISSUE 14): determinism digests, shadow auditing,
divergence latching, incident replay.

The acceptance bars: every request's rolling digest is a pure function
of (prompt, key schedule, model version, committed tokens) however the
stream was chunked, preempted, or failed over; the shadow auditor
catches a silently corrupted stream — and ONLY that stream; resumes
verify their committed buffers against the digest; the fleet's
digest-based failover prefix verification is equivalent to the old
buffered-list walk and additionally rejects version-mixed streams; and
a divergence flight dump replays into a bisected repro."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.fleet import FailoverDiverged, FleetRouter
from torchdistx_tpu.models import llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    DeterminismDiverged,
    Engine,
    Health,
)
from torchdistx_tpu.telemetry import audit
from torchdistx_tpu.telemetry import ops as tdx_ops

EOS = 5
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False,
)


@pytest.fixture(autouse=True)
def _clean():
    prev = telemetry.configure(collect=False, jsonl=None, flight=None)
    telemetry.reset()
    preemption.clear()
    yield
    faults.reset("")
    preemption.clear()
    tdx_ops.enable_tick_attribution(False)
    for plane in list(tdx_ops._PLANES.values()):
        plane.close()
    telemetry.configure(**prev)
    telemetry.reset()


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def solo(model, cfg, params, prompt, seed, max_new, *, eos=None,
         temperature=0.0, top_k=None):
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new, eos_id=eos,
        temperature=temperature, top_k=top_k,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


# ---------------------------------------------------------------------------
# DeterminismDigest


def test_digest_chunk_invariant_and_sensitive():
    """The digest is a pure function of (prompt, key, version, tokens)
    — identical whether tokens fold in per chunk or per token — and
    changes when ANY component changes."""
    key = audit.canonical_key(7)
    a = audit.DeterminismDigest(prompt_of(4), key)
    a.update([10, 11, 12, 13], "v1")
    b = audit.DeterminismDigest(prompt_of(4), key)
    for t in (10, 11, 12, 13):
        b.update([t], "v1")
    assert a.hexdigest() == b.hexdigest() and a.n == b.n == 4
    assert a.matches_stream(prompt_of(4), key, [10, 11, 12, 13], "v1")
    variants = [
        audit.DeterminismDigest.of_stream(
            prompt_of(4), key, [10, 11, 12, 99], "v1"),      # token
        audit.DeterminismDigest.of_stream(
            prompt_of(4), key, [10, 11, 12, 13], "v2"),      # version
        audit.DeterminismDigest.of_stream(
            prompt_of(4), audit.canonical_key(8), [10, 11, 12, 13], "v1"),
        audit.DeterminismDigest.of_stream(
            prompt_of(4, base=2), key, [10, 11, 12, 13], "v1"),
        audit.DeterminismDigest.of_stream(
            prompt_of(4), key, [10, 11, 12], "v1"),          # prefix only
    ]
    assert len({d.hexdigest() for d in variants} | {a.hexdigest()}) == 6
    # Snapshots roll: hexdigest() must not consume the state.
    assert a.hexdigest() == a.hexdigest()


def test_token_chunk_mapping():
    """Token 0 is the prefill's sample (chunk 0); decode chunk j
    commits tokens 1+(j-1)*dc .. j*dc."""
    assert audit.token_chunk(0, 4) == 0
    assert audit.token_chunk(1, 4) == 1
    assert audit.token_chunk(4, 4) == 1
    assert audit.token_chunk(5, 4) == 2
    assert audit.first_divergence([1, 2, 3], [1, 2, 4]) == 2
    assert audit.first_divergence([1, 2], [1, 2, 3]) == 2


def test_engine_stamps_digest_on_lifecycle_events(family):
    """Every request carries the rolling digest; its snapshots land on
    req.first_token (admitted identity) and req.finished (full stream),
    and the final digest equals an of_stream recomputation."""
    model, cfg, params = family
    telemetry.configure(collect=True)
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    h = eng.submit(prompt_of(5), max_new_tokens=6, key=11)
    toks = h.result()
    assert h.digest == audit.DeterminismDigest.of_stream(
        prompt_of(5), audit.canonical_key(11), toks, eng.model_version
    ).hexdigest()
    events = {
        r["name"]: r for r in telemetry.snapshot()["spans"]
        if r.get("type") == "event" and r.get("rid") == h._req.trace_id
    }
    assert events["req.finished"]["attrs"]["digest"] == h.digest
    assert "digest" in events["req.first_token"]["attrs"]
    # The replay identity rides req.submitted: a flight dump is a repro.
    sub = events["req.submitted"]["attrs"]
    assert sub["prompt"] == [int(t) for t in prompt_of(5)]
    assert len(sub["key"]) == 2
    eng.close()


# ---------------------------------------------------------------------------
# Satellite: idle ticks publish no attribution


def test_idle_ticks_skip_attribution_and_count(family):
    """A fully idle tick publishes NO per-tick attribution (gauges,
    serve.tick_s) — idle readings would dilute occupancy/goodput — and
    bumps serve.idle_ticks instead.  The FIRST idle tick zeroes the
    rate gauges once, so a quiet engine never advertises its last busy
    tick's goodput."""
    model, cfg, params = family
    tdx_ops.enable_tick_attribution(True)
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    h = eng.submit(prompt_of(4), max_new_tokens=6, key=0)
    busy_ticks = 0
    while not h.done:
        eng.step()
        busy_ticks += 1
    eid = eng.engine_id
    hist = telemetry.histograms()[f"serve.tick_s{{engine={eid}}}"]
    assert hist["count"] == busy_ticks  # every busy tick published
    idle_before = telemetry.counter("serve.idle_ticks").value
    for _ in range(5):
        eng.step()
    assert telemetry.counter("serve.idle_ticks").value == idle_before + 5
    assert (
        telemetry.histograms()[f"serve.tick_s{{engine={eid}}}"]["count"]
        == busy_ticks
    ), "idle ticks leaked into serve.tick_s"
    gauges = telemetry.gauges()
    for g in ("serve.occupancy", "serve.prefill_budget", "serve.churn",
              "serve.goodput"):
        assert gauges[f"{g}{{engine={eid}}}"] == 0, g  # zeroed on idle edge
    eng.close()


# ---------------------------------------------------------------------------
# Shadow auditor


def test_auditor_clean_traffic_no_divergence(family):
    """audit_sample=1.0 re-executes every completed request (after the
    user work, through the same programs) and finds nothing: replays
    are token-identical by construction."""
    model, cfg, params = family
    before = telemetry.counter("audit.checked").value
    eng = Engine(
        params, model=model, cfg=cfg, eos_id=EOS, audit_sample=1.0,
        temperature=0.8, top_k=8, **ENGINE_KW,
    )
    handles = [
        eng.submit(prompt_of(4 + i), max_new_tokens=6, key=100 + i)
        for i in range(3)
    ]
    eng.drain()  # drain() waits out the shadow audits too
    for i, h in enumerate(handles):
        assert h.result() == solo(
            model, cfg, params, prompt_of(4 + i), 100 + i, 6, eos=EOS,
            temperature=0.8, top_k=8,
        )
    st = eng.stats()
    assert st["audit_checked"] == 3
    assert st["audit_divergences"] == 0
    assert telemetry.counter("audit.checked").value == before + 3
    assert eng.health() is Health.READY
    assert eng.audit_backlog() == 0
    eng.close()


def test_auditor_off_by_default_and_sample_zero(family):
    model, cfg, params = family
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    assert eng._auditor is None and eng.audit_backlog() == 0
    eng.close()
    eng0 = Engine(
        params, model=model, cfg=cfg, audit_sample=0.0, **ENGINE_KW
    )
    assert eng0._auditor is None
    eng0.close()
    with pytest.raises(ValueError):
        Engine(params, model=model, cfg=cfg, audit_sample=1.5, **ENGINE_KW)


def test_bad_audit_sample_does_not_leak_ops_plane(family):
    """audit_sample validation runs BEFORE the ops-plane attach: a
    constructor that raises must not leave a half-built engine watched
    by a plane nothing will ever unwatch."""
    model, cfg, params = family
    with pytest.raises(ValueError):
        Engine(
            params, model=model, cfg=cfg, ops_port=0, audit_sample=2.0,
            ops_config=tdx_ops.OpsConfig(watchdog=False), **ENGINE_KW,
        )
    assert not tdx_ops._PLANES, "failed constructor leaked an ops plane"


def test_env_audit_sample(family, monkeypatch):
    model, cfg, params = family
    monkeypatch.setenv("TDX_AUDIT_SAMPLE", "1.0")
    eng = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    assert eng._auditor is not None and eng._auditor.sample == 1.0
    eng.close()
    monkeypatch.setenv("TDX_AUDIT_SAMPLE", "nope")
    with pytest.raises(ValueError):
        Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    monkeypatch.delenv("TDX_AUDIT_SAMPLE")


def test_corrupt_fault_flags_exactly_the_corrupted_stream(family):
    """Satellite: TDX_FAULT kind=corrupt at serve.step flips ONE
    committed token silently; the auditor must flag exactly that stream
    — and no others — with the right bisection."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=4, block_size=8,
        max_model_len=64, decode_chunk=4, max_prefills_per_tick=4,
        handle_preemption=False, audit_sample=1.0,
    )
    # All three streams decoding by chunk 4 (admission takes the first
    # ticks); the victim is the first decoding slot = first admitted.
    faults.reset("serve.step:4:corrupt")
    handles = [
        eng.submit(prompt_of(5), max_new_tokens=16, key=200 + i)
        for i in range(3)
    ]
    eng.drain()
    faults.reset("")
    assert telemetry.counter("serve.corruptions").value == 1
    st = eng.stats()
    assert st["audit_checked"] == 3
    assert st["audit_divergences"] == 1, (
        "auditor must flag exactly the corrupted stream"
    )
    detail = eng._auditor.divergence_detail[0]
    assert detail["rid"] == (
        handles[0]._req.trace_id or f"{eng.engine_id}-r0"
    )
    # The corrupted stream differs from ground truth at exactly one
    # token: the first committed token of the faulted chunk.
    truth = solo(model, cfg, params, prompt_of(5), 200, 16)
    got = handles[0].result()
    diffs = [i for i, (a, b) in enumerate(zip(truth, got)) if a != b]
    assert len(diffs) == 1
    assert detail["first_diverging_token"] == diffs[0]
    assert detail["first_diverging_chunk"] == audit.token_chunk(
        diffs[0], eng.decode_chunk
    )
    # The latch: OVERLOADED until an operator clears it.
    assert eng.health() is Health.OVERLOADED
    assert eng.stats()["diverging"] is True
    eid = eng.engine_id
    assert telemetry.gauges()[f"serve.diverging{{engine={eid}}}"] == 1
    eng.step()
    assert eng.health() is Health.OVERLOADED, "divergence must not self-clear"
    eng.clear_divergence()
    eng.step()
    assert eng.health() is Health.READY
    # The uncorrupted streams replayed clean.
    for i, h in enumerate(handles[1:], start=1):
        assert h.result() == solo(
            model, cfg, params, prompt_of(5), 200 + i, 16
        )
    eng.close()
    assert f"serve.diverging{{engine={eid}}}" not in telemetry.gauges()


def test_diverging_replica_routed_around(family):
    """A latched serve.diverging engine reads OVERLOADED: the router
    avoids it exactly like a stalled or storming replica."""
    model, cfg, params = family
    eng_a = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    eng_b = Engine(params, model=model, cfg=cfg, **ENGINE_KW)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_a._mark_diverging()
    for _ in range(3):
        assert router._pick().engine is eng_b
    h = router.submit(prompt_of(4), max_new_tokens=3, key=0)
    assert h.replica_id == 1
    assert h.result() == solo(model, cfg, params, prompt_of(4), 0, 3)
    router.close()


# ---------------------------------------------------------------------------
# Resume verification (preempt/replay/swap) against the digest


@pytest.mark.parametrize("sampled", [False, True])
def test_preempt_resume_digest_verified_ok(family, sampled):
    """Both preemption mechanisms resume through the digest check and
    stay token-identical — the equivalence half of the satellite: the
    digest-based verification accepts everything the old buffered-list
    behavior accepted, greedy AND sampled."""
    model, cfg, params = family
    sample_kw = dict(temperature=0.8, top_k=20) if sampled else {}
    # Drop-and-replay (slot pressure).
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=1,
        block_size=8, max_model_len=64, decode_chunk=4,
        handle_preemption=False, **sample_kw,
    )
    victim = eng.submit(prompt_of(6), max_new_tokens=24, key=700, priority=0)
    eng.step()
    assert not victim.done and len(victim._tokens) > 0
    eng.submit(prompt_of(6, base=3), max_new_tokens=8, key=701, priority=5)
    eng.drain()
    toks = victim.result()
    assert toks == solo(model, cfg, params, prompt_of(6), 700, 24, **sample_kw)
    assert victim.digest == audit.DeterminismDigest.of_stream(
        prompt_of(6), audit.canonical_key(700), toks, eng.model_version
    ).hexdigest()
    assert telemetry.counter("audit.divergences").value == 0
    eng.close()
    # Swap-to-host (page pressure).
    engs = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        handle_preemption=False, prefix_cache=False, **sample_kw,
    )
    victim = engs.submit(prompt_of(8), max_new_tokens=26, key=800, priority=0)
    engs.step()
    engs.submit(prompt_of(8, base=2), max_new_tokens=26, key=801, priority=5)
    engs.step()
    assert engs.allocator.num_swapped > 0
    engs.drain()
    assert victim.result() == solo(
        model, cfg, params, prompt_of(8), 800, 26, **sample_kw
    )
    assert telemetry.counter("audit.divergences").value == 0
    engs.close()


def test_replay_resume_rejects_corrupted_buffer(family):
    """Negative half: a committed-token buffer corrupted while the
    stream was parked fails the digest check typed
    (DeterminismDiverged) and latches the engine — never a silent
    poisoned continuation."""
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=1,
        block_size=8, max_model_len=64, decode_chunk=4,
        handle_preemption=False,
    )
    victim = eng.submit(prompt_of(6), max_new_tokens=24, key=700, priority=0)
    eng.step()
    assert len(victim._tokens) > 0
    urgent = eng.submit(
        prompt_of(6, base=3), max_new_tokens=8, key=701, priority=5
    )
    eng.step()  # victim preempted (drop-and-replay), requeued
    before = telemetry.counter("audit.divergences").value
    victim._tokens[0] ^= 1  # the corruption
    eng.drain()
    assert urgent.error is None
    with pytest.raises(DeterminismDiverged):
        victim.result()
    assert not victim.error.retryable
    assert telemetry.counter("audit.divergences").value == before + 1
    assert eng._diverging and eng.health() is Health.OVERLOADED
    assert eng.allocator.num_in_use == len(eng.prefix)  # pages came back
    eng.close()


def test_swap_resume_rejects_corrupted_buffer(family):
    model, cfg, params = family
    eng = Engine(
        params, model=model, cfg=cfg, scheduler="qos", num_slots=2,
        block_size=8, num_blocks=9, max_model_len=64, decode_chunk=4,
        handle_preemption=False, prefix_cache=False,
    )
    victim = eng.submit(prompt_of(8), max_new_tokens=26, key=800, priority=0)
    eng.step()
    urgent = eng.submit(
        prompt_of(8, base=2), max_new_tokens=26, key=801, priority=5
    )
    eng.step()  # victim swapped out
    assert eng.allocator.num_swapped > 0
    victim._tokens[-1] ^= 1  # corrupt the parked buffer
    eng.drain()
    assert urgent.error is None
    with pytest.raises(DeterminismDiverged):
        victim.result()
    assert eng.allocator.num_swapped == 0  # swap account settled
    assert eng.allocator.num_in_use == 0
    eng.close()


# ---------------------------------------------------------------------------
# Fleet failover: digest-based prefix verification


@pytest.mark.parametrize(
    "temperature,top_k", [(0.0, None), (0.8, 8)]
)
def test_failover_digest_equivalent_to_buffered_list(
    family, temperature, top_k
):
    """Kill + failover, greedy AND sampled: the digest-verified replay
    continues mid-stream token-identically, and the fleet handle's
    digest equals the single-engine digest of the same stream — the
    verification change is invisible wherever the old one accepted."""
    model, cfg, params = family
    kw = dict(
        temperature=temperature, top_k=top_k, eos_id=EOS,
        prefix_cache=False, **ENGINE_KW,
    )
    eng_a = Engine(params, model=model, cfg=cfg, **kw)
    eng_b = Engine(params, model=model, cfg=cfg, **kw)
    router = FleetRouter([eng_a, eng_b], version="v1")
    h = router.submit(prompt_of(6), max_new_tokens=10, key=3)
    g = h.tokens()
    first = [next(g), next(g)]
    eng_a.close()  # dies mid-stream; the iterator keeps going
    rest = list(g)
    toks = first + rest
    assert toks == solo(
        model, cfg, params, prompt_of(6), 3, 10, eos=EOS,
        temperature=temperature, top_k=top_k,
    )
    assert h.hops == 1
    assert h.digest == audit.DeterminismDigest.of_stream(
        prompt_of(6), audit.canonical_key(3), toks, "v0"
    ).hexdigest()
    router.close()


def test_failover_rejects_version_mixed_stream(family):
    """Satellite: a peer under the same ROUTER version tag but a
    different model_version produces byte-identical tokens here (same
    weights) — the old token-by-token walk would splice it silently;
    the digest, with model_version folded per token, rejects it
    typed."""
    model, cfg, params = family
    eng_a = Engine(
        params, model=model, cfg=cfg, model_version="weights-a",
        prefix_cache=False, **ENGINE_KW,
    )
    eng_b = Engine(
        params, model=model, cfg=cfg, model_version="weights-b",
        prefix_cache=False, **ENGINE_KW,
    )
    router = FleetRouter([eng_a, eng_b], version="v1")  # tags lie
    h = router.submit(prompt_of(6), max_new_tokens=8, key=0)
    g = h.tokens()
    consumed = [next(g), next(g)]
    assert consumed == solo(model, cfg, params, prompt_of(6), 0, 8)[:2]
    eng_a.close()
    with pytest.raises(FailoverDiverged) as ei:
        list(g)
    assert "model_version" in str(ei.value)
    assert h.done and h.error is ei.value
    router.close()


# ---------------------------------------------------------------------------
# Incident replay


def test_incident_replay_bisects_corrupt_dump(family, tmp_path):
    """Satellite: the divergence flight dump a corrupt fault produces
    replays into a repro — the clean re-run disagrees with the recorded
    digests, and the bisection lands on the faulted chunk."""
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts"),
    )
    import incident_replay

    model, cfg, params = family
    flight = str(tmp_path / "flight.jsonl")
    telemetry.configure(flight=flight, flight_capacity=4096)
    faults.reset("serve.step:3:corrupt")
    eng = Engine(
        params, model=model, cfg=cfg, num_slots=2, block_size=8,
        max_model_len=64, decode_chunk=4, max_prefills_per_tick=2,
        handle_preemption=False, audit_sample=1.0,
    )
    handles = [
        eng.submit(prompt_of(5), max_new_tokens=14, key=300 + i)
        for i in range(2)
    ]
    eng.drain()
    faults.reset("")
    st = eng.stats()
    assert st["audit_divergences"] == 1
    detail = eng._auditor.divergence_detail[0]
    eng.close()

    records = incident_replay.load_dump(flight)
    dumps = [r for r in records if r.get("type") == "flight_dump"]
    assert any(d.get("reason") == "divergence" for d in dumps)
    result = incident_replay.analyze(records, with_faults=True)
    assert result["reproduced"], result
    assert result["faulted_rerun_matches_incident"], result
    assert len(result["divergences"]) == 1
    row = result["divergences"][0]
    assert row["rid"] == detail["rid"]
    assert row["first_diverging_token"] == detail["first_diverging_token"]
    assert row["first_diverging_chunk"] == detail["first_diverging_chunk"]
    # Both streams rode the dump: the corrupted original and the
    # auditor's clean replay.
    ddump = next(d for d in dumps if d.get("reason") == "divergence")
    attrs = ddump["attrs"]
    assert attrs["expected_tokens"] != attrs["replayed_tokens"]
    for h in handles:
        assert h.error is None


def test_incident_replay_nothing_replayable(tmp_path):
    import sys

    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts"),
    )
    import incident_replay

    path = tmp_path / "empty.jsonl"
    path.write_text(json.dumps({"type": "flight_dump", "reason": "stall"})
                    + "\n")
    result = incident_replay.analyze(incident_replay.load_dump(str(path)))
    assert result["n_replayable"] == 0 and "error" in result
