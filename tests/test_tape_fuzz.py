"""Randomized differential tape test (VERDICT item 3, ISSUE 5 satellite).

The view/in-place/aliasing replay machinery — the analog of the
reference's hardest code (deferred_init.cc:529-666) — was covered only
by hand-picked cases.  This fuzzer generates bounded random programs of
views (slice / transpose / reshape / narrow), in-place ops (add_, mul_,
fill_, zero_, copy_, tril_, ...) and aliased writes (in-place through a
view, read through the base), executes each program THREE ways —

* eager torch (ground truth),
* deferred-init → ``materialize_tensor`` (torch tape replay),
* deferred-init → ``materialize_tensor_jax`` (JAX functional replay),

and asserts value equality across every target, for ~50 seeded programs,
with the native (C++) tape core on AND off (``TDX_DISABLE_NATIVE``).

RNG factories (``uniform_``/``randn``) are deliberately excluded: the
torch replay re-samples from the live global RNG and the JAX path uses
its own counter-based keys (documented in ``materialize.py``) — values
are substrate-defined, so only deterministic programs admit a three-way
differential.

Marked ``slow``: ~50 programs × one tiny XLA compile each.
"""

import os
import subprocess
import sys

import numpy as np
import pytest
import torch

import torchdistx_tpu.deferred_init as di
from torchdistx_tpu.deferred_init import materialize_tensor

jax = pytest.importorskip("jax")

from torchdistx_tpu.materialize import materialize_tensor_jax  # noqa: E402

pytestmark = pytest.mark.slow

# The native-core choice is CACHED at first use (_native.py load()/
# stack_ops() globals), so flipping TDX_DISABLE_NATIVE inside this
# process is a no-op — the Python-graph half must run in a subprocess
# that sets the env var before import, exactly like test_native_tape.py.
_FORCED_OFF = bool(os.environ.get("TDX_DISABLE_NATIVE"))


class _Gen:
    """Seeded program generator: each step is (op, operand ids, params),
    applied identically to any tensor environment.  Shapes are tracked
    host-side so every generated step is valid by construction."""

    N_STEPS = 14

    def __init__(self, seed: int):
        self.rng = np.random.default_rng(seed)
        self.steps = []
        self.shapes = {}  # id -> shape
        self._build()

    def _pick(self, pred=None):
        ids = [i for i, s in self.shapes.items() if pred is None or pred(s)]
        return int(self.rng.choice(ids)) if ids else None

    def _build(self):
        rng = self.rng
        # 2-3 deterministic factory bases.
        for i in range(int(rng.integers(2, 4))):
            r, c = int(rng.integers(2, 5)), int(rng.integers(2, 5))
            kind = rng.choice(["ones", "full", "arange", "eye"])
            self.steps.append(("factory", i, (str(kind), r, c)))
            self.shapes[i] = (r, c) if kind != "eye" else (r, r)
        nxt = len(self.shapes)
        for _ in range(self.N_STEPS):
            op = str(
                rng.choice(
                    [
                        "slice0", "transpose", "reshape_flat", "narrow",
                        "add_s", "mul_s", "sub_s", "div_s", "fill_",
                        "zero_", "tril_", "copy_", "add_t", "add",
                        "mul",
                    ]
                )
            )
            if op in ("slice0", "narrow"):
                src = self._pick(lambda s: s[0] >= 2)
                if src is None:
                    continue
                n0 = self.shapes[src][0]
                a = int(rng.integers(0, n0 - 1))
                ln = int(rng.integers(1, n0 - a + 1))
                self.steps.append((op, nxt, (src, a, ln)))
                self.shapes[nxt] = (ln,) + self.shapes[src][1:]
                nxt += 1
            elif op == "transpose":
                src = self._pick(lambda s: len(s) == 2)
                if src is None:
                    continue
                self.steps.append((op, nxt, (src,)))
                self.shapes[nxt] = self.shapes[src][::-1]
                nxt += 1
            elif op == "reshape_flat":
                src = self._pick()
                self.steps.append((op, nxt, (src,)))
                self.shapes[nxt] = (int(np.prod(self.shapes[src])),)
                nxt += 1
            elif op in ("add_s", "mul_s", "sub_s", "div_s", "fill_"):
                dst = self._pick()
                if op == "div_s":
                    # Power-of-two divisors only: XLA may divide via
                    # reciprocal-multiply, which for other divisors can
                    # differ from torch by 1 ulp — the differential bar
                    # here is BITWISE, so keep the arithmetic exact.
                    v = float(rng.choice([0.5, 2.0, 4.0]))
                else:
                    v = float(rng.integers(1, 5)) / 2.0
                self.steps.append((op, dst, (v,)))
            elif op == "zero_":
                self.steps.append((op, self._pick(), ()))
            elif op == "tril_":
                dst = self._pick(lambda s: len(s) == 2)
                if dst is None:
                    continue
                self.steps.append((op, dst, ()))
            elif op in ("copy_", "add_t"):
                dst = self._pick()
                src = self._pick(lambda s: s == self.shapes[dst])
                if src is None or src == dst:
                    continue
                self.steps.append((op, dst, (src,)))
            elif op in ("add", "mul"):
                a = self._pick()
                b = self._pick(lambda s: s == self.shapes[a])
                if b is None:
                    continue
                self.steps.append((op, nxt, (a, b)))
                self.shapes[nxt] = self.shapes[a]
                nxt += 1
        # Compare a handful of targets: always every base, plus up to 3
        # random later tensors (views and derived values).
        later = [i for i in self.shapes if i >= 3]
        extra = (
            [int(x) for x in rng.choice(later, min(3, len(later)), replace=False)]
            if later
            else []
        )
        self.targets = sorted(set(list(range(min(3, len(self.shapes)))) + extra))

    def execute(self):
        """Run the program on live torch tensors (eager under no mode,
        recorded when called inside a deferred-init context)."""
        env = {}
        for op, out, args in self.steps:
            if op == "factory":
                kind, r, c = args
                if kind == "ones":
                    env[out] = torch.ones(r, c)
                elif kind == "full":
                    env[out] = torch.full((r, c), 2.5)
                elif kind == "arange":
                    env[out] = torch.arange(r * c).float().reshape(r, c)
                else:
                    env[out] = torch.eye(r)
            elif op == "slice0":
                src, a, ln = args
                env[out] = env[src][a : a + ln]
            elif op == "narrow":
                src, a, ln = args
                env[out] = env[src].narrow(0, a, ln)
            elif op == "transpose":
                env[out] = env[args[0]].transpose(0, 1)
            elif op == "reshape_flat":
                env[out] = env[args[0]].reshape(-1)
            elif op == "add_s":
                env[out].add_(args[0])
            elif op == "mul_s":
                env[out].mul_(args[0])
            elif op == "sub_s":
                env[out].sub_(args[0])
            elif op == "div_s":
                env[out].div_(args[0])
            elif op == "fill_":
                env[out].fill_(args[0])
            elif op == "zero_":
                env[out].zero_()
            elif op == "tril_":
                env[out].tril_()
            elif op == "copy_":
                env[out].copy_(env[args[0]])
            elif op == "add_t":
                env[out].add_(env[args[0]])
            elif op == "add":
                env[out] = env[args[0]] + env[args[1]]
            elif op == "mul":
                env[out] = env[args[0]] * env[args[1]]
            else:  # pragma: no cover
                raise AssertionError(op)
        return env


def _run_differential(seed: int):
    prog = _Gen(seed)
    eager = prog.execute()
    # One fresh tape PER TARGET: materializing a target replays writers
    # on its storage up to its horizon, so a second target sharing a
    # mutated storage would read state advanced past its own read point
    # — the documented reason materialize_module merges stacks and
    # replays once chronologically.  Per-target isolation is the
    # well-defined materialize_tensor semantic under test here.
    for t in prog.targets:
        want = eager[t]
        with di._deferred_init_context():
            fakes = prog.execute()
        got_torch = materialize_tensor(fakes[t])
        assert torch.equal(got_torch, want), (
            f"seed {seed}: target {t} torch replay diverged\n"
            f"eager:\n{want}\nreplay:\n{got_torch}\n"
            f"program: {prog.steps}"
        )
        # Fresh tape again for the functional path (the torch replay
        # above already executed this tape's nodes for real).
        with di._deferred_init_context():
            fakes = prog.execute()
        got_jax = np.asarray(materialize_tensor_jax(fakes[t]))
        np.testing.assert_array_equal(
            got_jax, want.numpy(),
            err_msg=(
                f"seed {seed}: target {t} JAX functional replay diverged"
                f"\nprogram: {prog.steps}"
            ),
        )


@pytest.mark.parametrize("seed", range(25))
def test_fuzz_native(seed):
    if not _FORCED_OFF:
        from torchdistx_tpu import _native

        assert _native.native_available(), "native core should be live here"
    _run_differential(seed)


def test_fuzz_python_graph_subprocess():
    """Seeds 25-49 against the pure-Python graph, in a child process
    with ``TDX_DISABLE_NATIVE=1`` exported BEFORE import (the only way
    to actually disable the cached native core)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = f"""
import os
os.environ["TDX_DISABLE_NATIVE"] = "1"
import sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {os.path.join(repo, "tests")!r})
import jax
jax.config.update("jax_platforms", "cpu")
from torchdistx_tpu import _native
assert not _native.native_available(), "env var should disable native"
from test_tape_fuzz import _run_differential
for seed in range(25, 50):
    _run_differential(seed)
print("PYTHON-GRAPH-FUZZ-OK")
"""
    env = dict(os.environ)
    env.pop("TDX_DISABLE_NATIVE", None)
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        env=env,
    )
    assert proc.returncode == 0, (
        f"python-graph fuzz failed:\n{proc.stdout}\n{proc.stderr}"
    )
    assert "PYTHON-GRAPH-FUZZ-OK" in proc.stdout
