"""KV-cache decode: cached forward ≡ full forward; generation loop."""

import jax
import jax.numpy as jnp
import pytest

from torchdistx_tpu.models import gpt2, llama
from torchdistx_tpu.models.generate import generate


@pytest.fixture(scope="module", params=["llama", "gpt2"])
def family(request):
    if request.param == "llama":
        cfg = llama.llama_test()
        return llama, cfg
    cfg = gpt2.gpt2_test()
    return gpt2, cfg


def test_cached_prefill_matches_forward(family):
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 24), 0, cfg.vocab_size)
    full = model.forward(params, tokens, cfg, attn_impl="jnp")
    cache = model.init_cache(cfg, 2, 32)
    cached, _ = model.forward_cached(params, tokens, cfg, cache, 0)
    assert jnp.allclose(full, cached, atol=1e-4)


def test_incremental_decode_matches_forward(family):
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 12), 0, cfg.vocab_size)
    full = model.forward(params, tokens, cfg, attn_impl="jnp")
    # Feed token-by-token through the cache; last-position logits must match.
    cache = model.init_cache(cfg, 1, 16)
    outs = []
    for i in range(12):
        logits, cache = model.forward_cached(
            params, tokens[:, i : i + 1], cfg, cache, i
        )
        outs.append(logits[:, 0])
    stacked = jnp.stack(outs, axis=1)
    assert jnp.allclose(full, stacked, atol=1e-4)


def test_generate_greedy_matches_manual(family):
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0, cfg.vocab_size)
    out = generate(
        params, prompt, jax.random.PRNGKey(0),
        model=model, cfg=cfg, max_new_tokens=6, temperature=0.0,
    )
    assert out.shape == (2, 6)
    # Manual greedy rollout with the plain forward.
    seq = prompt
    for i in range(6):
        logits = model.forward(params, seq, cfg, attn_impl="jnp")
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        assert jnp.array_equal(out[:, i], nxt), f"step {i}"
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_generate_eos_padding(family):
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((1, 4), dtype=jnp.int32)
    out = generate(
        params, prompt, jax.random.PRNGKey(0),
        model=model, cfg=cfg, max_new_tokens=8, temperature=0.0,
        eos_id=int(jnp.argmax(
            model.forward(params, prompt, cfg, attn_impl="jnp")[0, -1]
        )),
    )
    # First sampled token IS the eos: everything after must be eos too.
    assert bool((out == out[0, 0]).all())


def test_generate_post_eos_semantics(family):
    """Pins post-EOS token semantics around the all-done early exit:
    (a) after a row's first eos, that row emits ONLY eos; (b) a row that
    has not finished keeps generating its normal greedy tokens (the
    early exit must not fire while anyone is live); (c) tokens equal the
    eos_id=None run up to each row's first eos (the exit changes cost,
    never values)."""
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(9), (2, 6), 0, cfg.vocab_size
    )
    free = generate(
        params, prompt, jax.random.PRNGKey(0),
        model=model, cfg=cfg, max_new_tokens=10, temperature=0.0,
    )
    # Pick an eos that row 0 emits mid-generation and row 1 never does —
    # row 0's token at step 2 (cfg-dependent but deterministic).
    eos = int(free[0, 2])
    if eos in [int(t) for t in free[1]]:
        pytest.skip("both rows emit the candidate eos; cfg-dependent")
    out = generate(
        params, prompt, jax.random.PRNGKey(0),
        model=model, cfg=cfg, max_new_tokens=10, temperature=0.0,
        eos_id=eos,
    )
    row0 = [int(t) for t in out[0]]
    first = row0.index(eos)
    assert first <= 2
    assert all(t == eos for t in row0[first:]), "post-eos must be all eos"
    assert row0[:first] == [int(t) for t in free[0, :first]]
    # Row 1 never hits eos: identical to the unconstrained run throughout.
    assert [int(t) for t in out[1]] == [int(t) for t in free[1]]


def test_generate_all_done_early_exit_value_preserving():
    """When EVERY row hits eos at the first token, the early-exit path
    serves all remaining steps — output must still be the eos fill.
    (llama only: the cond lives in model-agnostic generate.py.)"""
    model, cfg = llama, llama.llama_test()
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jnp.zeros((2, 4), dtype=jnp.int32)
    eos = int(jnp.argmax(
        model.forward(params, prompt, cfg, attn_impl="jnp")[0, -1]
    ))
    out = generate(
        params, prompt, jax.random.PRNGKey(0),
        model=model, cfg=cfg, max_new_tokens=12, temperature=0.0,
        eos_id=eos,
    )
    assert out.shape == (2, 12)
    assert bool((out == eos).all())


def test_generate_sampling_reproducible(family):
    model, cfg = family
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (1, 4), 0, cfg.vocab_size)
    a = generate(params, prompt, jax.random.PRNGKey(7), model=model, cfg=cfg,
                 max_new_tokens=5, temperature=0.8, top_k=20)
    b = generate(params, prompt, jax.random.PRNGKey(7), model=model, cfg=cfg,
                 max_new_tokens=5, temperature=0.8, top_k=20)
    assert jnp.array_equal(a, b)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()


def test_prep_decode_idempotent_and_value_preserving():
    """prep_decode fuses qkv and gate/up once (generate hoists it out of
    the token scan); it must be idempotent and change NOTHING about the
    cached forward's values."""
    import numpy as np

    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    prepped = llama.prep_decode(params, cfg)
    assert llama.prep_decode(prepped, cfg) is prepped  # idempotent
    assert "wqkv" in prepped["layers"] and "wgu" in prepped["layers"]
    assert "wq" not in prepped["layers"]

    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size
    )
    # Compare against the UNFUSED reference forward — forward_cached
    # fuses raw params through prep_decode internally, so a
    # prepped-vs-raw cached comparison would be tautological (both sides
    # would share a fusion bug, e.g. a wrong concat order).
    cache = llama.init_cache(cfg, 2, 8)
    logits_prepped, _ = llama.forward_cached(
        prepped, tokens, cfg, cache, 0
    )
    ref = llama.forward(params, tokens, cfg, attn_impl="jnp")
    np.testing.assert_allclose(
        np.asarray(logits_prepped),
        np.asarray(ref),
        atol=2e-5,
        err_msg="prep_decode changed cached-forward values",
    )
