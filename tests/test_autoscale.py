"""ISSUE 16: signal-driven elastic autoscaler — the observe→act loop.

The :class:`~torchdistx_tpu.fleet.Autoscaler` must scale out on
sustained occupancy / SLO burn / queue-slope prediction, scale in only
after a sustained quiet window, never flap inside the hysteresis band,
respect cooldowns and min/max bounds, replace latched-diverging replicas
instead of counting them as capacity, and reap STOPPED replicas from its
own control tick (no manual ``poll()``).  Rides along: the per-engine
``serve.queue_depth{engine=}`` gauge family (satellite 1), the router's
reap-listener supervision hook (satellite 2), and the SLOMonitor
burn-listener composition edge cases (satellite 3).
"""

import itertools

import jax
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.fleet import Autoscaler, AutoscaleConfig, FleetRouter
from torchdistx_tpu.models import llama
from torchdistx_tpu.serving import Engine, Health
from torchdistx_tpu.telemetry import ops

ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False, prefix_cache=False,
)


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def make_engine(family, **over):
    model, cfg, params = family
    kw = {**ENGINE_KW, **over}
    return Engine(params, model=model, cfg=cfg, **kw)


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


# ---------------------------------------------------------------------------
# Fake engines: the policy is pure control logic over the engine
# health/occupancy/queue surface, so the policy units run on duck-typed
# fakes (the real-engine integration rides below).


class _FakeScheduler:
    def __init__(self):
        self.n = 0

    def __len__(self):
        return self.n


class FakeEngine:
    _seq = itertools.count()

    def __init__(self, occ=0.0, queue=0, slots=4):
        self.engine_id = f"fake{next(FakeEngine._seq)}"
        self.num_slots = slots
        self.scheduler = _FakeScheduler()
        self.scheduler.n = queue
        self.occ = occ
        self.est = 0.01
        self._health = Health.READY
        self._diverging = False
        self.drain_steps = 1  # steps a drain takes to land at STOPPED
        self.closed = False

    def health(self):
        return self._health

    def est_ttft_s(self):
        return self.est

    def _n_running(self):
        return int(round(self.occ * self.num_slots))

    def begin_drain(self):
        if self._health is not Health.STOPPED:
            self._health = Health.DRAINING

    def step(self):
        if self._health is Health.DRAINING:
            self.drain_steps -= 1
            if self.drain_steps <= 0:
                self._health = Health.STOPPED

    def close(self):
        self._health = Health.STOPPED
        self.closed = True


def fake_fleet(n=1, cfg=None, monitor=None, **fake_kw):
    router = FleetRouter([])
    made = []

    def factory():
        eng = FakeEngine(**fake_kw)
        made.append(eng)
        return eng

    for _ in range(n):
        router.add_replica(factory())
    scaler = Autoscaler(
        router, factory, config=cfg, monitor=monitor
    )
    return router, scaler, made


def live(router):
    return [r for r in router.replicas()
            if r.engine.health() is not Health.DRAINING]


def set_occ(router, v):
    for rep in router.replicas():
        rep.engine.occ = v


# ---------------------------------------------------------------------------
# Policy: scale-out


def test_scale_out_on_sustained_occupancy():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3, fast_ticks=2)
    router, scaler, made = fake_fleet(1, cfg=cfg)
    set_occ(router, 0.95)
    assert scaler.tick() == "hold"  # one high tick is not sustained
    assert scaler.tick() == "occupancy"
    assert scaler.scale_outs == 1
    assert len(router.replicas()) == 2
    assert telemetry.gauges()["fleet.replicas_target"] == 2
    scaler.close()


def test_high_blip_does_not_scale():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3, fast_ticks=2)
    router, scaler, _ = fake_fleet(1, cfg=cfg)
    set_occ(router, 0.95)
    scaler.tick()
    set_occ(router, 0.1)  # blip over before the sustain window filled
    for _ in range(10):
        scaler.tick()
    assert scaler.scale_outs == 0
    assert len(router.replicas()) == 1
    scaler.close()


def test_hysteresis_band_never_flaps():
    """A signal oscillating INSIDE the band (above low water, below
    high water) must produce zero decisions in either direction."""
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=4, occupancy_low=0.3,
        occupancy_high=0.85, fast_ticks=1, slow_ticks=2,
        scale_out_cooldown=1, scale_in_cooldown=1,
    )
    router, scaler, _ = fake_fleet(2, cfg=cfg)
    for i in range(30):
        set_occ(router, 0.4 if i % 2 else 0.7)
        assert scaler.tick() == "hold"
    assert scaler.scale_outs == 0 and scaler.scale_ins == 0
    assert len(router.replicas()) == 2
    assert len(scaler.decisions) == 0
    scaler.close()


def test_scale_out_cooldown_and_max_bound():
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, fast_ticks=1, scale_out_cooldown=3,
    )
    # Every replica (including fresh spawns) reports saturated.
    router, scaler, _ = fake_fleet(1, cfg=cfg, occ=0.95)
    reasons = [scaler.tick() for _ in range(12)]
    outs = [i for i, r in enumerate(reasons) if r == "occupancy"]
    assert len(outs) == 2  # 1 → 2 → 3, then capped at max_replicas
    assert outs[1] - outs[0] >= cfg.scale_out_cooldown
    assert len(router.replicas()) == 3
    assert scaler.scale_outs == 2
    scaler.close()


def test_queue_slope_predictor_prescales():
    """Queue growth pre-scales BEFORE occupancy crosses its threshold."""
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, fast_ticks=5,
        slope_window=3, slope_high=2.0, occupancy_high=0.9,
    )
    router, scaler, made = fake_fleet(1, cfg=cfg, occ=0.5)
    for i in range(6):
        made[0].scheduler.n = 4 * i  # +4 requests per tick
        if scaler.tick() == "queue_slope":
            break
    assert scaler.scale_outs == 1
    assert ("queue_slope" in [d[1] for d in scaler.decisions])
    assert len(router.replicas()) == 2
    scaler.close()


# ---------------------------------------------------------------------------
# Policy: scale-in


def test_scale_in_lands_at_min_without_flap():
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=4, slow_ticks=2,
        scale_in_cooldown=2, scale_out_cooldown=1,
    )
    router, scaler, _ = fake_fleet(3, cfg=cfg)  # idle fleet of 3
    for _ in range(30):
        scaler.tick()
    assert len(router.replicas()) == 1
    assert scaler.scale_ins == 2
    assert scaler.scale_outs == 0  # never bounced back up
    assert telemetry.gauges()["fleet.replicas_target"] == 1
    # Victims were DRAINED (graceful), not closed.
    scaler.close()


def test_scale_in_blocked_at_min():
    cfg = AutoscaleConfig(
        min_replicas=2, max_replicas=4, slow_ticks=1,
        scale_in_cooldown=1, scale_out_cooldown=1,
    )
    router, scaler, _ = fake_fleet(2, cfg=cfg)
    for _ in range(10):
        scaler.tick()
    assert len(router.replicas()) == 2
    assert scaler.scale_ins == 0
    scaler.close()


def test_busy_fleet_does_not_scale_in():
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=4, slow_ticks=1,
        scale_in_cooldown=1, occupancy_low=0.3,
    )
    router, scaler, _ = fake_fleet(2, cfg=cfg, occ=0.5)  # inside the band
    for _ in range(10):
        scaler.tick()
    assert scaler.scale_ins == 0
    scaler.close()


# ---------------------------------------------------------------------------
# Policy: supervision, deficit repair, divergence replacement


def test_stopped_replica_reaped_and_respawned_below_min():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3)
    router, scaler, made = fake_fleet(1, cfg=cfg)
    reaped = []
    router.add_reap_listener(lambda rid, eng: reaped.append(rid))
    made[0].close()  # crash — user code never calls poll()
    assert scaler.tick() == "below_min"
    assert reaped == [0]
    reps = router.replicas()
    assert len(reps) == 1 and reps[0].engine is not made[0]
    scaler.close()


def test_diverging_replica_replaced_never_capacity():
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=3)
    router, scaler, made = fake_fleet(2, cfg=cfg)
    bad = made[0]
    bad._diverging = True
    assert scaler.tick() == "replace_diverging"
    assert scaler.replaces == 1
    assert bad.health() in (Health.DRAINING, Health.STOPPED)
    by_eng = {rep.engine for rep in router.replicas()}
    assert len([e for e in by_eng if e is not bad]) == 2  # replacement up
    # The drained incident engine is reaped by subsequent ticks.
    for _ in range(3):
        scaler.tick()
    assert bad not in {rep.engine for rep in router.replicas()}
    assert len(router.replicas()) == 2
    assert scaler.scale_outs == 0  # replacement is not load-driven growth
    scaler.close()


# ---------------------------------------------------------------------------
# Burn-signal consumption (satellite 3: the SLOMonitor edge cases the
# autoscaler depends on)


def _req_event(name, rid, ts, **attrs):
    return {"type": "event", "name": name, "rid": rid, "ts": ts,
            "attrs": attrs}


def _feed_terminal(mon, rid, ts, tenant="acme", ok=True):
    mon._on_record(_req_event("req.submitted", rid, ts, tenant=tenant))
    if ok:
        mon._on_record(_req_event("req.finished", rid, ts + 0.01))
    else:
        mon._on_record(
            _req_event("req.failed", rid, ts + 0.01,
                       error="DeadlineExceeded", retryable=False)
        )


def _slo_cfg(**over):
    kw = dict(slo=0.9, fast_window_s=10, slow_window_s=50,
              burn_threshold=2.0, min_samples=5)
    kw.update(over)
    return ops.SLOConfig(**kw)


def test_burn_fires_scale_out_then_refires_only_if_still_burning():
    mon = ops.SLOMonitor(_slo_cfg())
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=4, scale_out_cooldown=2,
    )
    router, scaler, _ = fake_fleet(1, cfg=cfg, monitor=mon)
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    assert scaler.tick() == "burn"
    assert scaler.scale_outs == 1
    # Burn persists: after the cooldown the LIVE monitor state re-fires.
    scaler.tick()
    assert scaler.scale_outs == 1  # inside cooldown
    scaler.tick()
    assert scaler.scale_outs == 2  # cooldown over, still burning
    scaler.close()
    mon.close()


def test_burn_clearing_mid_cooldown_does_not_double_fire():
    mon = ops.SLOMonitor(_slo_cfg())
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=4, scale_out_cooldown=4,
    )
    router, scaler, _ = fake_fleet(1, cfg=cfg, monitor=mon)
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    assert scaler.tick() == "burn"
    assert scaler.scale_outs == 1
    # The burn CLEARS while the cooldown still runs (a genuine
    # recovery transition: the bad window ages out).
    for i in range(20):
        _feed_terminal(mon, 100 + i, t0 + 60 + i * 0.1, ok=True)
    assert mon.burning() == {"acme": False}
    assert scaler.recoveries == 1
    # Past the cooldown: the stale edge latch must NOT fire a second
    # scale-out — decision time re-checks the live monitor.  (The now
    # idle extra replica MAY scale back in: that's the recovery working,
    # not a flap.)
    reasons = [scaler.tick() for _ in range(10)]
    assert all(r in ("hold", "quiet") for r in reasons)
    assert scaler.scale_outs == 1
    scaler.close()
    mon.close()


def test_idle_pruned_tenant_is_not_a_recovery():
    mon = ops.SLOMonitor(_slo_cfg())
    cfg = AutoscaleConfig(min_replicas=1, max_replicas=4)
    router, scaler, _ = fake_fleet(1, cfg=cfg, monitor=mon)
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    scaler.tick()
    assert scaler.scale_outs == 1
    # The tenant goes silent and the monitor prunes it for idleness.
    with mon._lock:
        mon._prune_idle(t0 + 10_000.0)
    # NOT a recovery: no burning=False edge reached the listener, the
    # gauge left the registry rather than reading 0.
    assert scaler.recoveries == 0
    assert not any(
        t == "acme" and not burning
        for _, t, burning in scaler.burn_events
    )
    assert "serve.slo_burning{tenant=acme}" not in telemetry.gauges()
    scaler.close()
    mon.close()


def test_burn_listeners_compose_with_primary_order_pinned():
    calls = []
    mon = ops.SLOMonitor(_slo_cfg(
        on_burn=lambda tenant, info: calls.append("primary"),
    ))
    mon.add_burn_listener(
        lambda tenant, burning, info: calls.append(("l1", burning)))
    mon.add_burn_listener(
        lambda tenant, burning, info: calls.append(("l2", burning)))
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    # BOTH ran — the listener API composes with (never replaces) the
    # primary on_burn — and the primary ran FIRST.
    assert calls == ["primary", ("l1", True), ("l2", True)]
    # Recovery edges reach listeners (with info=None semantics) but not
    # the primary (which is the burn-incident action).
    for i in range(20):
        _feed_terminal(mon, 100 + i, t0 + 60 + i * 0.1, ok=True)
    assert calls == [
        "primary", ("l1", True), ("l2", True), ("l1", False), ("l2", False),
    ]
    mon.close()


def test_default_flight_dump_still_runs_under_listeners():
    """With no custom on_burn, registering a listener must not silence
    the default flight-dump action (the pre-listener behavior)."""
    calls = []
    mon = ops.SLOMonitor(_slo_cfg())
    mon._default_on_burn = lambda tenant, info: calls.append("default")
    mon.add_burn_listener(
        lambda tenant, burning, info: calls.append("listener"))
    t0 = 1000.0
    for i in range(8):
        _feed_terminal(mon, i, t0 + i * 0.1, ok=False)
    assert calls == ["default", "listener"]
    mon.close()


# ---------------------------------------------------------------------------
# Config validation


def test_config_validation():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(occupancy_low=0.9, occupancy_high=0.8).validate()
    with pytest.raises(ValueError):
        AutoscaleConfig(fast_ticks=0).validate()


# ---------------------------------------------------------------------------
# Satellite 1: the per-engine serve.queue_depth{engine=} family (real
# engines, both scheduler flavors)


@pytest.mark.parametrize("sched", ["fifo", "qos"])
def test_queue_depth_per_engine_family_two_engines(family, sched):
    eng_a = make_engine(family, scheduler=sched)
    eng_b = make_engine(family, scheduler=sched)
    ha = [eng_a.submit(prompt_of(4, base=1 + i), max_new_tokens=2, key=i)
          for i in range(3)]
    hb = [eng_b.submit(prompt_of(4, base=10), max_new_tokens=2, key=9)]
    g = telemetry.gauges()
    # N replicas in one process: the labeled family keeps them apart
    # (the unlabeled gauge is whichever engine wrote last).
    assert g[f"serve.queue_depth{{engine={eng_a.engine_id}}}"] == 3
    assert g[f"serve.queue_depth{{engine={eng_b.engine_id}}}"] == 1
    for h in ha + hb:
        h.result()
    g = telemetry.gauges()
    assert g[f"serve.queue_depth{{engine={eng_a.engine_id}}}"] == 0
    assert g[f"serve.queue_depth{{engine={eng_b.engine_id}}}"] == 0
    # STOPPED prunes the family — absent from the registry, not 0.
    eng_a.close()
    g = telemetry.gauges()
    assert f"serve.queue_depth{{engine={eng_a.engine_id}}}" not in g
    assert f"serve.queue_depth{{engine={eng_b.engine_id}}}" in g
    eng_b.close()
    assert (f"serve.queue_depth{{engine={eng_b.engine_id}}}"
            not in telemetry.gauges())


# ---------------------------------------------------------------------------
# Satellite 2 + integration: supervision over real engines


def test_supervision_reaps_and_prunes_without_manual_poll(family):
    eng_a = make_engine(family)
    eng_b = make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    scaler = Autoscaler(
        router, lambda: make_engine(family),
        config=AutoscaleConfig(min_replicas=1, max_replicas=3),
    )
    reaped = []
    router.add_reap_listener(lambda rid, eng: reaped.append(eng.engine_id))
    key_a = f"serve.queue_depth{{engine={eng_a.engine_id}}}"
    assert key_a in telemetry.gauges()
    eng_a.close()  # replica dies; nobody calls router.poll()
    scaler.tick()
    assert reaped == [eng_a.engine_id]
    assert [rep.engine for rep in router.replicas()] == [eng_b]
    assert key_a not in telemetry.gauges()
    scaler.close()
    router.close()


def test_scale_in_drains_gracefully_real_engines(family):
    cfg = AutoscaleConfig(
        min_replicas=1, max_replicas=3, slow_ticks=2,
        scale_in_cooldown=1, scale_out_cooldown=1,
    )
    eng_a = make_engine(family)
    eng_b = make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    scaler = Autoscaler(router, lambda: make_engine(family), config=cfg)
    h = router.submit(prompt_of(4), max_new_tokens=3, key=0)
    assert len(h.result()) == 3
    for _ in range(20):
        scaler.tick()
        if len(router.replicas()) == 1:
            break
    assert len(router.replicas()) == 1
    assert scaler.scale_ins == 1
    survivor = router.replicas()[0].engine
    retired = eng_a if survivor is eng_b else eng_b
    assert retired.health() is Health.STOPPED
    assert (f"serve.queue_depth{{engine={retired.engine_id}}}"
            not in telemetry.gauges())
    # The survivor still serves.
    h2 = router.submit(prompt_of(4, base=3), max_new_tokens=2, key=1)
    assert len(h2.result()) == 2
    scaler.close()
    router.close()
