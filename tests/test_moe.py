"""MoE family: routing semantics, expert-parallel training on the mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from torchdistx_tpu.models import moe
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh


@pytest.fixture(scope="module")
def cfg():
    return moe.moe_test()


@pytest.fixture(scope="module")
def params(cfg):
    return moe.init_params(jax.random.PRNGKey(0), cfg)


def test_forward_shape_and_finite(cfg, params):
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, aux = moe.forward(params, tokens, cfg, attn_impl="jnp",
                              return_aux=True)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())
    # Balanced-ish routing at init: aux ≈ 1 (perfectly uniform = 1.0).
    assert 0.5 < float(aux) < 4.0


def test_moe_ffn_full_capacity_matches_dense_math(cfg):
    """With capacity ≥ all tokens and k = E (route to every expert), the MoE
    FFN must equal the prob-weighted sum of every expert's dense FFN."""
    c = dataclasses.replace(
        cfg, experts_per_token=cfg.n_experts, capacity_factor=float(cfg.n_experts)
    )
    key = jax.random.PRNGKey(3)
    b, s, d = 2, 8, c.dim
    h = jax.random.normal(key, (b, s, d), dtype=jnp.float32)
    e, f = c.n_experts, c.ffn_dim
    router = jax.random.normal(jax.random.fold_in(key, 1), (d, e)) * 0.1
    eg = jax.random.normal(jax.random.fold_in(key, 2), (e, d, f)) * 0.1
    eu = jax.random.normal(jax.random.fold_in(key, 3), (e, d, f)) * 0.1
    ed = jax.random.normal(jax.random.fold_in(key, 4), (e, f, d)) * 0.1

    out, _ = moe.moe_ffn(h, router, eg, eu, ed, c)

    probs = jax.nn.softmax((h.reshape(-1, d) @ router), axis=-1)
    dense = jnp.stack(
        [
            (jax.nn.silu(h.reshape(-1, d) @ eg[i]) * (h.reshape(-1, d) @ eu[i]))
            @ ed[i]
            for i in range(e)
        ],
        axis=1,
    )  # (T, E, D)
    ref = (dense * probs[..., None]).sum(axis=1).reshape(b, s, d)
    assert jnp.allclose(out, ref, atol=1e-4)


def test_capacity_drops_tokens(cfg):
    """With capacity 1 and many tokens, most selections are dropped — output
    must stay finite and bounded."""
    c = dataclasses.replace(cfg, capacity_factor=0.01)
    key = jax.random.PRNGKey(5)
    h = jax.random.normal(key, (2, 16, c.dim), dtype=jnp.float32)
    e, d, f = c.n_experts, c.dim, c.ffn_dim
    out, _ = moe.moe_ffn(
        h,
        jax.random.normal(jax.random.fold_in(key, 1), (d, e)) * 0.1,
        jax.random.normal(jax.random.fold_in(key, 2), (e, d, f)) * 0.1,
        jax.random.normal(jax.random.fold_in(key, 3), (e, d, f)) * 0.1,
        jax.random.normal(jax.random.fold_in(key, 4), (e, f, d)) * 0.1,
        c,
    )
    assert bool(jnp.isfinite(out).all())
    # capacity 1 per expert → at most E*C = 4 selections kept; most tokens
    # produce zero output.
    zero_rows = (jnp.abs(out).max(axis=-1) == 0).sum()
    assert int(zero_rows) >= 16 * 2 - 4 * 2


def test_expert_parallel_train_step(cfg):
    mesh = make_mesh(MeshSpec(fsdp=2, ep=4))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.adamw(1e-2), model=moe, attn_impl="jnp",
        nonfinite_guard=False,
    )
    state = init_fn(jax.random.PRNGKey(0))
    assert state.params["layers"]["e_gate"].sharding.spec[1] == "ep"
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(4):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_ep_sharded_matches_unsharded(cfg, params):
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, cfg.vocab_size)
    ref = moe.forward(params, tokens, cfg, attn_impl="jnp")
    mesh = make_mesh(MeshSpec(ep=8))
    from jax.sharding import NamedSharding
    from torchdistx_tpu.parallel.sharding import fit_shardings

    shardings = fit_shardings(
        moe.param_specs(cfg), moe.abstract_params(cfg), mesh
    )
    sharded = jax.tree.map(jax.device_put, params, shardings)
    out = jax.jit(
        lambda p, t: moe.forward(p, t, cfg, attn_impl="jnp")
    )(sharded, tokens)
    assert jnp.allclose(ref, out, atol=1e-4)


def test_pipeline_moe_forward_matches_dense(cfg, params):
    """pp+MoE: logits match the dense path when capacity is ample (routing
    happens per microbatch, but with no drops the computation is
    identical); the aux channel survives the pipeline."""
    big_cap = dataclasses.replace(cfg, capacity_factor=4.0)
    tokens = jax.random.randint(
        jax.random.PRNGKey(5), (4, 16), 0, cfg.vocab_size
    )
    ref, ref_aux = moe.forward(
        params, tokens, big_cap, attn_impl="jnp", return_aux=True
    )
    mesh = make_mesh(axis_names=("fsdp", "pp"), shape=(4, 2))
    out, aux = jax.jit(
        lambda p, t: moe.forward(
            p, t, big_cap, attn_impl="jnp", mesh=mesh, pp_axis="pp",
            n_microbatches=2, return_aux=True,
        )
    )(params, tokens)
    assert jnp.allclose(ref, out, atol=1e-4)
    assert jnp.isfinite(aux) and float(aux) > 0.0
    # Per-microbatch aux is an estimator of the full-batch aux.
    assert abs(float(aux) - float(ref_aux)) < 0.5


def test_pipeline_expert_parallel_train_step(cfg):
    """pp × ep on one mesh: the NotImplementedError combination of round 1."""
    cfg2 = dataclasses.replace(cfg, n_layers=2)
    mesh = make_mesh(MeshSpec(pp=2, ep=4))
    init_fn, step_fn = ts.make_train_step(
        cfg2, mesh, optax.sgd(0.1), model=moe, pp_axis="pp",
        n_microbatches=2, attn_impl="jnp", nonfinite_guard=False,
    )
    state = init_fn(jax.random.PRNGKey(0))
    assert state.params["layers"]["e_gate"].sharding.spec[:2] == ("pp", "ep")
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg2.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(4):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
