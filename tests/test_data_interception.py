"""`Tensor.data` interception under deferred init — the ProxyVariableHooks
analog (reference: deferred_init.cc:888-1127).

The reference records `variable_data()` / `set_data()` as synthetic ops
because `nn.Parameter` / `Tensor.data` bypass the dispatcher.  Here the read
path flows through the wrapper subclass (dispatched ops on the `.data` alias
record normally); the setter is intercepted on FakeTensor (fake.py) because
torch's `set_data` swaps the TensorImpl underneath the Python object, which
would silently orphan the deferred-init record.
"""

import numpy as np
import pytest
import torch
import torch.nn as nn

import torchdistx_tpu.deferred_init as di

try:
    import jax  # noqa: F401

    from torchdistx_tpu.materialize import materialize_module_jax

    HAS_JAX = True
except ImportError:
    HAS_JAX = False

needs_jax = pytest.mark.skipif(not HAS_JAX, reason="jax unavailable")


class DataMutatingInit(nn.Module):
    """The HF `_init_weights` pattern: in-place ops through `.data`."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)
        self.lin.weight.data.fill_(3.0)
        self.lin.bias.data.zero_()


class DataAssignInit(nn.Module):
    """`param.data = <fake tensor>` (set_data with a recorded RHS)."""

    def __init__(self):
        super().__init__()
        self.lin = nn.Linear(4, 4)
        self.lin.weight.data = torch.full((4, 4), 7.0)


def test_data_inplace_torch_replay():
    m = di.deferred_init(DataMutatingInit)
    di.materialize_module(m)
    assert torch.equal(m.lin.weight.data, torch.full((4, 4), 3.0))
    assert torch.equal(m.lin.bias.data, torch.zeros(4))


@needs_jax
def test_data_inplace_jax_replay():
    m = di.deferred_init(DataMutatingInit)
    out = materialize_module_jax(m)
    np.testing.assert_allclose(np.asarray(out["lin.weight"]), 3.0)
    np.testing.assert_allclose(np.asarray(out["lin.bias"]), 0.0)


def test_set_data_fake_torch_replay():
    m = di.deferred_init(DataAssignInit)
    assert di.is_deferred(m.lin.weight)
    di.materialize_module(m)
    assert torch.equal(m.lin.weight.data, torch.full((4, 4), 7.0))


@needs_jax
def test_set_data_fake_jax_replay():
    m = di.deferred_init(DataAssignInit)
    out = materialize_module_jax(m)
    np.testing.assert_allclose(np.asarray(out["lin.weight"]), 7.0)


def test_set_data_external_real_tensor():
    ext = torch.arange(9.0).reshape(3, 3)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(3, 3)
            self.lin.weight.data = ext

    m = di.deferred_init(M)
    di.materialize_module(m)
    assert torch.equal(m.lin.weight.data, ext)


@needs_jax
def test_set_data_external_real_tensor_jax():
    ext = torch.arange(9.0).reshape(3, 3)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(3, 3)
            self.lin.weight.data = ext

    m = di.deferred_init(M)
    out = materialize_module_jax(m)
    np.testing.assert_allclose(
        np.asarray(out["lin.weight"]), ext.numpy()
    )


def test_set_data_external_guard_fires():
    ext = torch.ones(3, 3)

    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(3, 3)
            self.lin.weight.data = ext

    m = di.deferred_init(M)
    ext.add_(1)  # mutate after recording
    with pytest.raises(RuntimeError, match="mutated after recording"):
        di.materialize_module(m)


def test_set_data_shape_change():
    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(2, 2)
            self.lin.weight.data = torch.zeros(5, 2)

    m = di.deferred_init(M)
    assert tuple(m.lin.weight.shape) == (5, 2)
    di.materialize_module(m)
    assert tuple(m.lin.weight.shape) == (5, 2)
    assert torch.equal(m.lin.weight.data, torch.zeros(5, 2))


def test_set_data_outside_context_real_raises():
    m = di.deferred_init(nn.Linear, 4, 4)
    with pytest.raises(RuntimeError, match="outside of a deferred-init"):
        m.weight.data = torch.zeros(4, 4)


def test_data_read_feeds_compute():
    class M(nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)
            self.lin.weight.data.fill_(1.0)
            # A read through .data feeding a new parameter.
            self.scaled = nn.Parameter(self.lin.weight.data * 2)

    m = di.deferred_init(M)
    di.materialize_module(m)
    assert torch.equal(m.scaled.data, torch.full((4, 4), 2.0))
