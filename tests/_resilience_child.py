"""Worker for the crash/preemption-resume e2e (test_crash_resume.py).

Launched as ``python tests/_resilience_child.py <ckpt_dir> <n_steps>
<steps_log>`` with ``TDX_FAULT`` optionally set in the environment.  Runs
``fit()`` on the deterministic rig in :func:`run_training`; appends one
line per EXECUTED optimizer step to ``steps_log`` (flushed immediately,
so a hard ``os._exit`` crash cannot hide steps), and prints one
``RESULT {...}`` JSON line on orderly exits.

``run_training`` is also imported by the parent test for the
uninterrupted reference run — the "identical computation" contract lives
in exactly one place (the same pattern as tests/_mp_worker.py).
"""

import json
import os
import sys

if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])


def run_training(ckpt_dir, n_steps, on_step=None):
    """Deterministic tiny run: llama_test on a dp=8 virtual mesh, SGD,
    fixed data stream.  Returns ``(state, metrics)`` from fit()."""
    import jax
    import optax

    from torchdistx_tpu.models import llama
    from torchdistx_tpu.parallel import train_step as ts
    from torchdistx_tpu.parallel.fit import fit
    from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh

    cfg = llama.llama_test()
    mesh = make_mesh(MeshSpec(dp=8))
    init_fn, step_fn = ts.make_train_step(cfg, mesh, optax.sgd(0.1))
    bs = ts.batch_sharding(mesh)

    def batches():
        key = jax.random.PRNGKey(42)
        while True:
            key, sub = jax.random.split(key)
            t = jax.device_put(
                jax.random.randint(sub, (8, 16), 0, cfg.vocab_size), bs
            )
            yield {"tokens": t, "targets": t}

    return fit(
        init_fn,
        step_fn,
        batches(),
        key=jax.random.PRNGKey(0),
        n_steps=n_steps,
        checkpoint_dir=ckpt_dir,
        checkpoint_every=2,
        # Synchronous saves: a `crash` fault must not race an in-flight
        # background write — the replay window stays exactly
        # checkpoint_every wide even under a hard kill.
        checkpoint_sync=True,
        on_metrics=on_step,
    )


def digest(state) -> float:
    import jax
    import numpy as np

    return float(
        sum(np.float64(np.asarray(l).astype("float64").sum())
            for l in jax.tree.leaves(state.params))
    )


def main() -> None:
    ckpt_dir, n_steps, steps_log = sys.argv[1], int(sys.argv[2]), sys.argv[3]

    log = open(steps_log, "a", buffering=1)

    def on_step(step, metrics):
        # One line per executed step, flushed before the next dispatch:
        # the parent asserts no step ever runs twice across crash+resume.
        log.write(f"{step}\n")
        log.flush()
        os.fsync(log.fileno())

    from torchdistx_tpu import telemetry

    state, _ = run_training(ckpt_dir, n_steps, on_step=on_step)
    # fit() clears the preemption flag once it has acted on it, so the
    # counter (not the flag) is the post-hoc evidence of a preemption.
    print(
        "RESULT "
        + json.dumps(
            {
                "final_step": int(state.step),
                "digest": digest(state),
                "preempted": telemetry.counters().get(
                    "train.preemptions", 0
                ) > 0,
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
