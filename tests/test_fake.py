"""Fake-tensor unit tests — parity with /root/reference/tests/python/test_fake.py
plus TPU-claim coverage the reference cannot have."""

import pytest
import torch

from torchdistx_tpu import fake


def test_fake_cpu_tensor():
    with fake.fake_mode():
        t = torch.ones([10, 10])
    assert fake.is_fake(t)
    assert t.device == torch.device("cpu")
    assert t.shape == (10, 10)


def test_fake_cuda_tensor_without_cuda():
    # Reference: fake CUDA tensors constructible on CUDA-less hosts
    # (test_fake.py:13-20 verifies the device-guard spoof).
    with fake.fake_mode(fake_cuda=True):
        t = torch.ones([10], device="cuda")
    assert fake.is_fake(t)
    assert t.device.type == "cuda"


def test_fake_tpu_tensor():
    with fake.fake_mode():
        t = torch.ones([8, 128], device="tpu")
    assert fake.is_fake(t)
    assert t.device.type == "tpu"


def test_fake_mode_default_device():
    with fake.fake_mode(device="tpu"):
        t = torch.zeros([4, 4])
    assert fake.is_fake(t)
    assert t.device.type == "tpu"


def test_ops_on_fake_outside_mode():
    # The Fake "dispatch key" lives on the tensor, not only in TLS
    # (fake.cc:129-150): ops on fakes work after the mode exits.
    with fake.fake_mode():
        t = torch.ones([4, 8])
    u = t @ t.t()
    assert fake.is_fake(u)
    assert u.shape == (4, 4)


def test_fake_no_storage_allocation():
    with fake.fake_mode():
        t = torch.empty([1 << 16, 1 << 16])  # 16 GiB if real
    assert fake.is_fake(t)
    # The wrapper subclass carries a storage descriptor but never allocates:
    # touching the data must fail rather than page in 16 GiB.
    with pytest.raises(RuntimeError, match="not allocated|invalid python storage"):
        t.untyped_storage().data_ptr()


def test_mixed_fake_devices_error():
    with fake.fake_mode():
        a = torch.ones([4], device="tpu")
        b = torch.ones([4], device="cpu")
    with pytest.raises(RuntimeError, match="mixed devices"):
        a + b


def test_meta_like():
    # Reference test_fake.py:43-53.
    with fake.fake_mode():
        t = torch.ones([10, 10])
    m = fake.meta_like(t)
    assert m.device.type == "meta"
    assert m.shape == t.shape
    assert m.dtype == t.dtype


def test_meta_like_non_fake_raises():
    # Reference test_fake.py:56-60.
    with pytest.raises(ValueError):
        fake.meta_like(torch.ones([2]))


def test_repr_marks_fake():
    # Reference fake.py:15-40 repr patch.
    with fake.fake_mode():
        t = torch.ones([2, 3], device="tpu")
    assert "fake=True" in repr(t)
    assert "tpu" in repr(t)


def test_real_ops_unaffected_under_mode():
    real = torch.arange(6.0)
    with fake.fake_mode():
        out = real * 2
    assert not fake.is_fake(out)
    assert torch.equal(out, torch.arange(6.0) * 2)


def test_fake_module_construction():
    with fake.fake_mode():
        m = torch.nn.Linear(128, 256, device="tpu")
    assert fake.is_fake(m.weight)
    assert m.weight.device.type == "tpu"
    assert isinstance(m.weight, torch.nn.Parameter)
    assert m.weight.requires_grad


def test_fake_inplace_and_views():
    with fake.fake_mode():
        t = torch.zeros([4, 4])
        u = t.view(16)
        t.add_(1)
    assert fake.is_fake(u)
    assert u.shape == (16,)
    # In-place op returns the same fake wrapper (fake.cc:507-523).
    v = t.mul_(2)
    assert v is t


def test_tpu_spoof_persists_after_mode_exit():
    """Pins the DELIBERATE exit asymmetry vs the reference's scoped
    device-guard spoof (fake.cc:574-586): the "tpu" rename persists after
    every fake mode exits — the name must keep resolving for fakes that
    outlive their mode (the deferred-init flow) — but no fake hardware
    becomes reachable: a REAL tpu allocation still fails at dispatch.
    See docs/fake_tensor.md, "Deliberate exit asymmetry"."""
    with fake.fake_mode():
        t = torch.ones(3, device="tpu")
    # After exit: the device string still parses, and the escaped fake
    # still reports it.
    assert torch.device("tpu").type == "tpu"
    assert t.device.type == "tpu"
    # But the spoof registered no kernels: a non-fake tpu tensor cannot
    # actually be created outside a fake mode.
    with pytest.raises(RuntimeError):
        torch.ones(3, device="tpu")
