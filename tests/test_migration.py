"""Live KV-page stream migration (ISSUE 17): warm failover, zero-recompute
drain, prefill/decode disaggregation.

A live decoding stream must move between same-version engines at the
KV-page level — pages gathered to host on the source, digest-verified
and scattered on the destination, the same ``fold_in(key, n_gen)``
schedule continuing — with not one token recomputed, lost, or changed
(greedy AND sampled, prefix cache on AND off).  Shared/CoW prefix pages
migrate as a self-contained private set; refcounts settle to exactly
the index-owned set on the source and a private set on the destination
— zero leaked pages, zero phantom swapped pages, on both engines.
Incompatible imports fail typed (``MigrationIncompatible``) BEFORE any
scatter; injected faults at ``serve.migrate_out`` leave the source
stream running untouched, at ``serve.migrate_in`` free the partial page
set and fall back to the cold key-pinned replay.  A stream whose
deadline expires mid-migration surfaces ``DeadlineExceeded`` once.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchdistx_tpu import telemetry
from torchdistx_tpu.fleet import FleetRouter
from torchdistx_tpu.models import llama
from torchdistx_tpu.models.generate import generate
from torchdistx_tpu.resilience import faults, preemption
from torchdistx_tpu.serving import (
    DeadlineExceeded,
    Engine,
    Health,
    MigrationIncompatible,
    RequestPreempted,
)

EOS = 5
ENGINE_KW = dict(
    num_slots=2, block_size=8, max_model_len=64, decode_chunk=4,
    handle_preemption=False, prefix_cache=False,
)


@pytest.fixture(autouse=True)
def _clean():
    preemption.clear()
    yield
    preemption.clear()
    faults.reset("")


@pytest.fixture(scope="module")
def family():
    cfg = llama.llama_test()
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    return llama, cfg, params


def solo(family, prompt, seed, max_new, *, eos=None, temperature=0.0,
         top_k=None):
    model, cfg, params = family
    out = generate(
        params, jnp.asarray(prompt)[None], jax.random.PRNGKey(seed),
        model=model, cfg=cfg, max_new_tokens=max_new, eos_id=eos,
        temperature=temperature, top_k=top_k,
    )
    toks = [int(t) for t in np.asarray(out)[0]]
    if eos is not None and eos in toks:
        toks = toks[: toks.index(eos) + 1]
    return toks


def prompt_of(n, base=1):
    return np.arange(base, base + n, dtype=np.int32)


def make_engine(family, **over):
    model, cfg, params = family
    kw = {**ENGINE_KW, **over}
    return Engine(params, model=model, cfg=cfg, **kw)


def settled(eng):
    """Zero leaked pages: only index-owned pages remain in use, nothing
    phantom-swapped."""
    held = 0 if eng.prefix is None else len(eng.prefix)
    return (
        eng.allocator.num_in_use == held and eng.allocator.num_swapped == 0
    )


# ---------------------------------------------------------------------------
# Warm migration: token parity across the engine hop


@pytest.mark.parametrize(
    "temperature,top_k", [(0.0, None), (0.8, 8)], ids=["greedy", "sampled"]
)
@pytest.mark.parametrize("cache", [False, True], ids=["nocache", "cache"])
def test_migration_token_identical(family, temperature, top_k, cache):
    """The tentpole invariant: a stream migrated mid-decode continues on
    the peer token-identically — zero recompute, zero divergence —
    greedy and sampled, prefix cache on and off."""
    kw = dict(temperature=temperature, top_k=top_k, eos_id=EOS,
              prefix_cache=cache)
    eng_a, eng_b = make_engine(family, **kw), make_engine(family, **kw)
    router = FleetRouter([eng_a, eng_b], version="v1")
    before = telemetry.counter("fleet.migrations").value
    h = router.submit(prompt_of(6), max_new_tokens=10, key=3)
    src = h.replica_id
    src_eng = eng_a if src == 0 else eng_b
    dst_eng = eng_b if src == 0 else eng_a
    g = h.tokens()
    first = [next(g), next(g)]
    (slot,) = src_eng.migratable_slots()
    assert router.migrate_stream(src, slot)
    rest = list(g)
    expect = solo(family, prompt_of(6), 3, 10, eos=EOS,
                  temperature=temperature, top_k=top_k)
    assert first + rest == expect
    assert telemetry.counter("fleet.migrations").value == before + 1
    assert src_eng.stats()["migrations_out"] == 1
    assert dst_eng.stats()["migrations_in"] == 1
    assert src_eng.stats()["recoveries"] == 0  # zero recompute
    assert dst_eng.stats()["recoveries"] == 0
    assert settled(eng_a) and settled(eng_b)


def test_shared_prefix_pages_migrate_and_refcounts_settle(family):
    """A prefix-cached stream holds SHARED (CoW) pages; its migration
    must resolve them into a self-contained private set on the
    destination while the source settles to exactly the index-owned
    pages (refcount 1 each) — no leak, no phantom swap, either side."""
    eng_a = make_engine(family, prefix_cache=True)
    eng_b = make_engine(family, prefix_cache=True)
    router = FleetRouter([eng_a, eng_b], version="v1")
    prompt = prompt_of(16)  # two full pages at block_size=8: indexable
    # Warm A's prefix index (route there explicitly), and pin routing so
    # the second submission shares its pages.
    eng_b.detector.observe_tick(50.0)
    warm = router.submit(prompt, max_new_tokens=2, key=0)
    assert warm.replica_id == 0 and len(warm.result()) == 2
    assert len(eng_a.prefix) == 2
    eng_b.detector.observe_tick(50.0)  # A's real ticks must stay cheaper
    h = router.submit(prompt, max_new_tokens=8, key=1)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    (slot,) = eng_a.migratable_slots()
    req = eng_a._slot_req[slot]
    shared = [p for p in req.blocks if eng_a.allocator.refcount(p) > 1]
    assert shared, "the stream must actually hold shared prefix pages"
    assert router.migrate_stream(0, slot)
    # Source: the stream's refs dropped; the index-owned set remains,
    # every page at exactly refcount 1.
    assert eng_a.allocator.num_in_use == len(eng_a.prefix) == 2
    assert all(
        eng_a.allocator.refcount(p) == 1 for p in eng_a.prefix._pages.values()
    )
    assert eng_a.allocator.num_swapped == 0
    # Destination: a fully private copy — every page refcount 1, none
    # known to B's (empty) index.
    assert len(eng_b.prefix) == 0
    assert all(eng_b.allocator.refcount(p) == 1 for p in req.blocks)
    assert eng_b.allocator.num_swapped == 0
    rest = list(g)
    assert first + rest == solo(family, prompt, 1, 8)
    assert settled(eng_a) and settled(eng_b)


def test_drain_by_migration(family):
    """Graceful scale-in/hot-swap drain: migrate_out_streams empties a
    replica with zero recomputed tokens; the drain then completes
    immediately and the stream finishes on the peer."""
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    h = router.submit(prompt_of(6), max_new_tokens=10, key=7)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    router.close_admission(0)
    out = router.migrate_out_streams(0)
    assert out == {"migrated": 1, "fallbacks": 0, "left": 0}
    eng_a.begin_drain()
    while eng_a.health() is not Health.STOPPED:
        eng_a.step()
    rest = list(g)
    assert first + rest == solo(family, prompt_of(6), 7, 10)
    assert eng_b.stats()["migrations_in"] == 1
    assert eng_b.stats()["recoveries"] == 0
    assert settled(eng_b)


# ---------------------------------------------------------------------------
# Typed incompatibility + fallback-to-replay


def test_geometry_mismatch_typed_before_scatter(family):
    """An incompatible snapshot must be rejected BEFORE any page
    scatter — typed, destination pool untouched."""
    eng_a = make_engine(family)
    eng_b = make_engine(family, block_size=16)  # incompatible geometry
    h = eng_a.submit(prompt_of(6), max_new_tokens=8, key=0)
    g = h.tokens()
    next(g)
    (slot,) = eng_a.migratable_slots()
    snapshot = eng_a.migrate_out(slot)
    with pytest.raises(MigrationIncompatible) as ei:
        eng_b.migrate_in(snapshot)
    assert ei.value.retryable
    assert eng_b.allocator.num_in_use == 0  # nothing allocated, no leak
    assert not h.done  # the stream is fine — a cold replay reproduces it


def test_incompatible_import_falls_back_to_cold_replay(family):
    """Router path: export succeeds, every candidate refuses the import
    → the stream falls back to the key-pinned cold replay, counted on
    fleet.migration_fallbacks, still token-identical."""
    eng_a = make_engine(family)
    eng_b = make_engine(family, block_size=16)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    before = telemetry.counter("fleet.migration_fallbacks").value
    h = router.submit(prompt_of(6), max_new_tokens=10, key=9)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    (slot,) = eng_a.migratable_slots()
    assert not router.migrate_stream(0, slot)
    assert telemetry.counter("fleet.migration_fallbacks").value == before + 1
    inner_err = h._inner.error
    assert isinstance(inner_err, RequestPreempted) and inner_err.retryable
    rest = list(g)  # the FleetHandle re-binds and replays
    assert first + rest == solo(family, prompt_of(6), 9, 10)
    assert h.hops == 1
    assert settled(eng_a) and settled(eng_b)


def test_version_pinned_no_destination_leaves_stream_running(family):
    """Migration is version-pinned like failover: with no same-version
    peer the stream is skipped — left running, never failed."""
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a], version="v1")
    router.add_replica(eng_b, version="v2")
    h = router.submit(prompt_of(6), max_new_tokens=8, key=2)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    (slot,) = eng_a.migratable_slots()
    assert not router.migrate_stream(0, slot)
    rest = list(g)  # untouched: finishes on the source
    assert first + rest == solo(family, prompt_of(6), 2, 8)
    assert eng_a.stats()["migrations_out"] == 0


# ---------------------------------------------------------------------------
# Fault injection at the migration sites


def test_fault_migrate_out_leaves_source_untouched(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    faults.reset("serve.migrate_out:1:io")
    h = router.submit(prompt_of(6), max_new_tokens=8, key=4)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    (slot,) = eng_a.migratable_slots()
    assert not router.migrate_stream(0, slot)
    rest = list(g)  # still on A, still token-identical
    assert first + rest == solo(family, prompt_of(6), 4, 8)
    assert h.hops == 0
    assert eng_a.stats()["migrations_out"] == 0
    assert eng_b.stats()["migrations_in"] == 0
    assert settled(eng_a) and settled(eng_b)


def test_fault_migrate_in_frees_pages_and_falls_back(family):
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    faults.reset("serve.migrate_in:1:io")
    before = telemetry.counter("fleet.migration_fallbacks").value
    h = router.submit(prompt_of(6), max_new_tokens=10, key=6)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    (slot,) = eng_a.migratable_slots()
    assert not router.migrate_stream(0, slot)
    # The partially-imported page set was freed on the destination.
    assert eng_b.allocator.num_in_use == 0
    assert telemetry.counter("fleet.migration_fallbacks").value == before + 1
    rest = list(g)  # cold replay, token-identical
    assert first + rest == solo(family, prompt_of(6), 6, 10)
    assert settled(eng_a) and settled(eng_b)


# ---------------------------------------------------------------------------
# Deadline accounting across migration


def test_deadline_travels_with_the_stream(family):
    """The ABSOLUTE deadline migrates with the request: remaining time
    shrinks by migration wall-clock exactly as across failover hops."""
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    h = router.submit(prompt_of(6), max_new_tokens=8, key=1,
                      deadline_s=60.0)
    g = h.tokens()
    next(g)
    (slot,) = eng_a.migratable_slots()
    req = eng_a._slot_req[slot]
    deadline_before = req.deadline
    assert router.migrate_stream(0, slot)
    assert req.deadline == deadline_before  # same absolute instant
    list(g)


def test_deadline_expired_mid_migration_single_terminal(family):
    """A stream whose deadline expires at migration time is NOT
    exported (nothing to double-serve) and surfaces DeadlineExceeded
    exactly once — the idempotent _fail keeps the first terminal."""
    eng_a, eng_b = make_engine(family), make_engine(family)
    router = FleetRouter([eng_a, eng_b], version="v1")
    eng_b.detector.observe_tick(0.5)
    h = router.submit(prompt_of(6), max_new_tokens=32, key=8,
                      deadline_s=60.0)
    g = h.tokens()
    next(g)
    (slot,) = eng_a.migratable_slots()
    req = eng_a._slot_req[slot]
    req.deadline = time.perf_counter() - 0.001  # expires "mid-migration"
    assert not router.migrate_stream(0, slot)
    assert eng_a.stats()["migrations_out"] == 0
    with pytest.raises(DeadlineExceeded):
        list(g)
    first_err = h.error
    assert isinstance(first_err, DeadlineExceeded)
    # A late second terminal (e.g. a racing migration fallback) must not
    # replace the first: _fail is idempotent.
    h._inner._fail(RequestPreempted("late loser"))
    assert h._inner.error is not None
    assert h.error is first_err
    assert settled(eng_a) and settled(eng_b)


# ---------------------------------------------------------------------------
# Prefill/decode disaggregation


def test_role_steering_and_rebalance(family):
    """Long prompts route to the prefill-role replica; router.step()'s
    rebalance ships the decode phase to the decode-role peer mid-stream
    — token-identically.  Short prompts never land on prefill."""
    eng_p = make_engine(family, role="prefill")
    eng_d = make_engine(family, role="decode")
    router = FleetRouter(version="v1", long_prompt_tokens=16)
    router.add_replica(eng_p, version="v1")
    router.add_replica(eng_d, version="v1")
    # Short prompt: steered OFF the prefill replica regardless of load.
    hs = router.submit(prompt_of(4), max_new_tokens=2, key=0)
    assert hs.replica_id == 1
    assert len(hs.result()) == 2
    # Long prompt: lands on prefill...
    before = telemetry.counter("fleet.migrations").value
    h = router.submit(prompt_of(24), max_new_tokens=8, key=5)
    assert h.replica_id == 0
    g = h.tokens()
    first = [next(g)]
    # ...and the control loop hands its decode phase to the decode peer.
    assert router.rebalance() == 1
    assert telemetry.counter("fleet.migrations").value == before + 1
    rest = list(g)
    assert first + rest == solo(family, prompt_of(24), 5, 8)
    assert eng_p.stats()["migrations_out"] == 1
    assert eng_d.stats()["migrations_in"] == 1
    assert eng_d.stats()["recoveries"] == 0
    assert settled(eng_p) and settled(eng_d)
    stats = router.stats()
    assert [r["role"] for r in stats["replicas"]] == ["prefill", "decode"]


def test_role_validation_and_gauge(family):
    with pytest.raises(ValueError):
        make_engine(family, role="turbo")
    eng = make_engine(family, role="decode")
    eid = eng.engine_id
    assert telemetry.gauges().get(f"serve.role{{engine={eid}}}") == "decode"
    eng.close()
    assert f"serve.role{{engine={eid}}}" not in telemetry.gauges()
