"""Pipeline parallelism: GPipe schedule ≡ plain scan, training works."""

import jax
import jax.numpy as jnp
import optax
import pytest

from torchdistx_tpu.models import gpt2, llama
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.parallel.pipeline import pipeline_forward


def test_generic_pipeline_matches_scan():
    mesh = make_mesh(axis_names=("dp", "pp"), shape=(2, 4))
    key = jax.random.PRNGKey(0)
    L, B, D = 8, 4, 16
    w = jax.random.normal(key, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    ref = x
    for i in range(L):
        ref = block(ref, w[i])

    out = jax.jit(
        lambda x, w: pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=2
        )
    )(x, w)
    assert jnp.allclose(ref, out, atol=1e-5)


def test_pipeline_grads_match_scan():
    mesh = make_mesh(axis_names=("pp",), shape=(8,))
    key = jax.random.PRNGKey(0)
    L, B, D = 8, 4, 8
    w = jax.random.normal(key, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    def loss_scan(w):
        h, _ = jax.lax.scan(lambda h, wl: (block(h, wl), None), x, w)
        return (h**2).sum()

    def loss_pp(w):
        h = pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=4
        )
        return (h**2).sum()

    g_ref = jax.grad(loss_scan)(w)
    g_pp = jax.jit(jax.grad(loss_pp))(w)
    assert jnp.allclose(g_ref, g_pp, atol=1e-4)


@pytest.mark.parametrize("model_mod,make_cfg", [
    (llama, llama.llama_test),
    (gpt2, gpt2.gpt2_test),
])
def test_model_pipeline_forward_matches(model_mod, make_cfg):
    import dataclasses

    cfg = dataclasses.replace(make_cfg(), n_layers=4)
    mesh = make_mesh(axis_names=("fsdp", "pp"), shape=(2, 4))
    params = model_mod.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    ref = model_mod.forward(params, tokens, cfg, attn_impl="jnp")
    out = jax.jit(
        lambda p, t: model_mod.forward(
            p, t, cfg, attn_impl="jnp", mesh=mesh, pp_axis="pp",
            n_microbatches=2,
        )
    )(params, tokens)
    assert jnp.allclose(ref, out, atol=1e-4)


def test_pipeline_skips_invalid_tick_compute():
    """Ramp-up/drain ticks take a `lax.cond` identity branch — the compiled
    module keeps a real HLO conditional (stage FLOPs are skipped at runtime,
    not select-executed), in the forward and the transposed backward."""
    mesh = make_mesh(axis_names=("dp", "pp"), shape=(2, 4))
    L, B, D = 4, 4, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    fwd = jax.jit(
        lambda x, w: pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=2
        )
    )
    assert "conditional" in fwd.lower(x, w).compile().as_text()

    bwd = jax.jit(jax.grad(
        lambda w: (
            pipeline_forward(
                x, w, block, mesh=mesh, axis="pp", n_microbatches=2
            ) ** 2
        ).sum()
    ))
    assert "conditional" in bwd.lower(w).compile().as_text()


def test_pipeline_train_step():
    import dataclasses

    cfg = dataclasses.replace(llama.llama_test(), n_layers=4)
    mesh = make_mesh(axis_names=("tp", "pp"), shape=(2, 4))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.sgd(0.1), pp_axis="pp", n_microbatches=2,
        attn_impl="jnp",
    )
    state = init_fn(jax.random.PRNGKey(0))
    # layer dim sharded over pp
    assert state.params["layers"]["wq"].sharding.spec[0] == "pp"
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(3):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]
