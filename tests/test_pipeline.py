"""Pipeline parallelism: GPipe schedule ≡ plain scan, training works."""

import jax
import jax.numpy as jnp
import optax
import pytest

from torchdistx_tpu.models import gpt2, llama
from torchdistx_tpu.parallel import train_step as ts
from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.parallel.pipeline import pipeline_forward


def test_generic_pipeline_matches_scan():
    mesh = make_mesh(axis_names=("dp", "pp"), shape=(2, 4))
    key = jax.random.PRNGKey(0)
    L, B, D = 8, 4, 16
    w = jax.random.normal(key, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    ref = x
    for i in range(L):
        ref = block(ref, w[i])

    out = jax.jit(
        lambda x, w: pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=2
        )
    )(x, w)
    assert jnp.allclose(ref, out, atol=1e-5)


def test_pipeline_grads_match_scan():
    mesh = make_mesh(axis_names=("pp",), shape=(8,))
    key = jax.random.PRNGKey(0)
    L, B, D = 8, 4, 8
    w = jax.random.normal(key, (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.fold_in(key, 1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    def loss_scan(w):
        h, _ = jax.lax.scan(lambda h, wl: (block(h, wl), None), x, w)
        return (h**2).sum()

    def loss_pp(w):
        h = pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=4
        )
        return (h**2).sum()

    g_ref = jax.grad(loss_scan)(w)
    g_pp = jax.jit(jax.grad(loss_pp))(w)
    assert jnp.allclose(g_ref, g_pp, atol=1e-4)


@pytest.mark.parametrize("model_mod,make_cfg", [
    (llama, llama.llama_test),
    (gpt2, gpt2.gpt2_test),
])
def test_model_pipeline_forward_matches(model_mod, make_cfg):
    import dataclasses

    cfg = dataclasses.replace(make_cfg(), n_layers=4)
    mesh = make_mesh(axis_names=("fsdp", "pp"), shape=(2, 4))
    params = model_mod.init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    ref = model_mod.forward(params, tokens, cfg, attn_impl="jnp")
    out = jax.jit(
        lambda p, t: model_mod.forward(
            p, t, cfg, attn_impl="jnp", mesh=mesh, pp_axis="pp",
            n_microbatches=2,
        )
    )(params, tokens)
    assert jnp.allclose(ref, out, atol=1e-4)


def test_pipeline_skips_invalid_tick_compute():
    """Ramp-up/drain ticks take a `lax.cond` identity branch — the compiled
    module keeps a real HLO conditional (stage FLOPs are skipped at runtime,
    not select-executed), in the forward and the transposed backward."""
    mesh = make_mesh(axis_names=("dp", "pp"), shape=(2, 4))
    L, B, D = 4, 4, 8
    w = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.3
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

    def block(h, wl):
        return jnp.tanh(h @ wl)

    fwd = jax.jit(
        lambda x, w: pipeline_forward(
            x, w, block, mesh=mesh, axis="pp", n_microbatches=2
        )
    )
    assert "conditional" in fwd.lower(x, w).compile().as_text()

    bwd = jax.jit(jax.grad(
        lambda w: (
            pipeline_forward(
                x, w, block, mesh=mesh, axis="pp", n_microbatches=2
            ) ** 2
        ).sum()
    ))
    assert "conditional" in bwd.lower(w).compile().as_text()


def test_pipeline_train_step():
    import dataclasses

    cfg = dataclasses.replace(llama.llama_test(), n_layers=4)
    mesh = make_mesh(axis_names=("tp", "pp"), shape=(2, 4))
    init_fn, step_fn = ts.make_train_step(
        cfg, mesh, optax.sgd(0.1), pp_axis="pp", n_microbatches=2,
        attn_impl="jnp", nonfinite_guard=False,
    )
    state = init_fn(jax.random.PRNGKey(0))
    # layer dim sharded over pp
    assert state.params["layers"]["wq"].sharding.spec[0] == "pp"
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}
    losses = []
    for _ in range(3):
        state, m = step_fn(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


# ---------------------------------------------------------------------------
# 1F1B (hand-interleaved backward, O(P) live activations)


def test_1f1b_grads_match_unpipelined():
    import dataclasses

    from torchdistx_tpu.parallel import pipeline

    cfg = dataclasses.replace(llama.llama_test(), n_layers=4)
    params = llama.init_params(jax.random.PRNGKey(0), cfg)
    B, S, M, P = 8, 32, 8, 4
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, cfg.vocab_size)

    ref_loss, ref_grads = jax.value_and_grad(llama.loss_fn)(
        params, tokens, targets, cfg
    )
    mesh = make_mesh(MeshSpec(fsdp=2, pp=P))
    loss, grads = jax.jit(
        lambda p, t, g: llama.pp_value_and_grad(
            p, t, g, cfg, mesh=mesh, pp_axis="pp", n_microbatches=M
        )
    )(params, tokens, targets)
    assert jnp.allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: None
        if jnp.allclose(a, b, atol=2e-5)
        else pytest.fail("grad mismatch"),
        ref_grads,
        grads,
    )
    # The memory contract: ring buffer holds 3P/2+1 microbatch activations —
    # strictly fewer than the M + P - 1 tick-saves GPipe autodiff keeps
    # live at M = 2P.
    assert pipeline.last_stash_slots == 3 * P // 2 + 1
    assert pipeline.last_stash_slots < M + P - 1


def test_1f1b_gpt2_tied_embedding_grads_match():
    """GPT-2's tied wte rides the shared_params channel: used by both the
    stage-0 embed and the last-stage head, carried with ONE vocab-sized
    f32 accumulator, and its 1F1B gradient (sum of both psum'd
    contributions) must match unpipelined autodiff of the tied forward."""
    import dataclasses

    from torchdistx_tpu.parallel import pipeline

    cfg = dataclasses.replace(gpt2.gpt2_test(), n_layers=4)
    params = gpt2.init_params(jax.random.PRNGKey(0), cfg)
    B, S, M = 8, 32, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, cfg.vocab_size)

    ref_loss, ref_grads = jax.value_and_grad(gpt2.loss_fn)(
        params, tokens, targets, cfg
    )
    mesh = make_mesh(MeshSpec(fsdp=2, pp=4))
    loss, grads = jax.jit(
        lambda p, t, g: gpt2.pp_value_and_grad(
            p, t, g, cfg, mesh=mesh, pp_axis="pp", n_microbatches=M
        )
    )(params, tokens, targets)
    assert jnp.allclose(loss, ref_loss, rtol=1e-5)
    jax.tree.map(
        lambda a, b: None
        if jnp.allclose(a, b, atol=2e-5)
        else pytest.fail("gpt2 1f1b grad mismatch"),
        ref_grads,
        grads,
    )
    # The memory contract: the tied (V, D) embedding is accumulated ONCE
    # (g_sp) — duplicating it into both ep and hp would carry two
    # vocab-sized f32 buffers through every tick of the scan.
    vocab_f32 = [
        (name, shape)
        for name, shape, dtype in pipeline.last_grad_acc_shapes
        if shape[:1] == (cfg.vocab_size,) and dtype == "float32"
    ]
    assert len(vocab_f32) == 1 and vocab_f32[0][0] == "g_sp", vocab_f32


def test_1f1b_train_step_matches_gpipe():
    import dataclasses

    cfg = dataclasses.replace(llama.llama_test(), n_layers=4)
    mesh = make_mesh(axis_names=("tp", "pp"), shape=(2, 4))
    tokens = jax.device_put(
        jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size),
        ts.batch_sharding(mesh),
    )
    batch = {"tokens": tokens, "targets": tokens}

    def run(schedule):
        init_fn, step_fn = ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), pp_axis="pp", n_microbatches=8,
            pp_schedule=schedule, attn_impl="jnp", nonfinite_guard=False,
        )
        state = init_fn(jax.random.PRNGKey(0))
        losses = []
        for _ in range(3):
            state, m = step_fn(state, batch)
            losses.append(float(m["loss"]))
        return losses

    gpipe = run("gpipe")
    onefb = run("1f1b")
    # Same optimization trajectory (same grads up to accumulation order).
    for a, b in zip(gpipe, onefb):
        assert abs(a - b) < 2e-3, (gpipe, onefb)
    assert onefb[-1] < onefb[0]


@pytest.mark.skipif(
    bool(__import__("os").environ.get("CI")),
    reason="wall-clock comparison: meaningless on loaded shared CI runners",
)
@pytest.mark.slow  # tier-1 re-budget (ISSUE 9): heavy; slow lane
def test_1f1b_wallclock_not_worse_than_gpipe():
    """At M = 2P with rematerialized blocks, 1F1B's tick count (2M + 2P - 3)
    carries the same total compute as GPipe's forward+transpose — assert
    compiled wall-clock parity within generous slack (CPU timing)."""
    import dataclasses
    import time

    cfg = dataclasses.replace(
        llama.llama_test(), n_layers=4, dim=128, ffn_dim=256, remat=True
    )
    mesh = make_mesh(
        axis_names=("pp",), shape=(4,), devices=jax.devices()[:4]
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 64), 0, cfg.vocab_size)
    batch = {"tokens": tokens, "targets": tokens}

    def timed(schedule):
        init_fn, step_fn = ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), pp_axis="pp", n_microbatches=8,
            pp_schedule=schedule, attn_impl="jnp", nonfinite_guard=False,
        )
        state = init_fn(jax.random.PRNGKey(0))
        state, m = step_fn(state, batch)  # compile
        float(m["loss"])
        best = float("inf")
        for _ in range(3):  # best-of-3: shield against scheduler stalls
            t0 = time.perf_counter()
            for _ in range(5):
                state, m = step_fn(state, batch)
            float(m["loss"])
            best = min(best, time.perf_counter() - t0)
        return best

    t_gpipe = timed("gpipe")
    t_1f1b = timed("1f1b")
    # CPU lockstep timing is noisy even best-of-3 (asymmetric CI load
    # between the two phases); 1.75 slack still catches the failure mode
    # that matters — the ~2x wall of a serialized fwd/bwd schedule.
    assert t_1f1b <= 1.75 * t_gpipe, (t_1f1b, t_gpipe)


def test_1f1b_moe_pytree_activations_match_gpipe():
    """MoE's router aux-loss rides the 1F1B pipeline as a pytree side
    channel; loss must match the GPipe path (identical per-microbatch
    routing semantics) and grads must be finite."""
    import dataclasses

    from torchdistx_tpu.models import moe

    cfg = dataclasses.replace(moe.moe_test(), n_layers=4)
    params = moe.init_params(jax.random.PRNGKey(0), cfg)
    B, S, M = 8, 16, 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, cfg.vocab_size)
    targets = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0, cfg.vocab_size)
    mesh = make_mesh(MeshSpec(fsdp=2, pp=4))

    ref_loss, ref_grads = jax.jit(
        jax.value_and_grad(
            lambda p, t, g: moe.loss_fn(
                p, t, g, cfg, mesh=mesh, pp_axis="pp", n_microbatches=M,
                attn_impl="jnp",
            )
        )
    )(params, tokens, targets)
    loss, grads = jax.jit(
        lambda p, t, g: moe.pp_value_and_grad(
            p, t, g, cfg, mesh=mesh, pp_axis="pp", n_microbatches=M,
            attn_impl="jnp",
        )
    )(params, tokens, targets)
    assert jnp.allclose(loss, ref_loss, rtol=1e-5), (loss, ref_loss)
    jax.tree.map(
        lambda a, b: None
        if jnp.allclose(a, b, atol=3e-5)
        else pytest.fail("moe 1f1b grad mismatch"),
        ref_grads,
        grads,
    )


class _NoPP:
    """Model-module stand-in implementing the base protocol but no
    pp_value_and_grad (every in-tree family now has one)."""

    __name__ = "nopp"
    param_specs = staticmethod(llama.param_specs)
    abstract_params = staticmethod(llama.abstract_params)
    init_params = staticmethod(llama.init_params)
    loss_fn = staticmethod(llama.loss_fn)


def test_1f1b_rejects_custom_loss_and_unsupported_model():
    import dataclasses

    cfg = dataclasses.replace(llama.llama_test(), n_layers=4)
    mesh = make_mesh(
        axis_names=("pp",), shape=(4,), devices=jax.devices()[:4]
    )
    with pytest.raises(ValueError, match="custom loss_fn"):
        ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), pp_axis="pp", pp_schedule="1f1b",
            loss_fn=lambda p, t, g: 0.0,
        )
    with pytest.raises(ValueError, match="pp_value_and_grad"):
        ts.make_train_step(
            cfg, mesh, optax.sgd(0.1), pp_axis="pp",
            pp_schedule="1f1b", model=_NoPP(),
        )
