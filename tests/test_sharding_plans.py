"""Sharding-plan builders and the shared mesh-fitting rules."""

import jax
from jax.sharding import PartitionSpec as P

from torchdistx_tpu.parallel.mesh import MeshSpec, make_mesh
from torchdistx_tpu.parallel.sharding import (
    combine_plans,
    fit_spec_to_mesh,
    fsdp_over,
    fsdp_plan,
    replicate_indivisible,
    tp_plan_llama,
)


def test_combine_plans_honors_explicit_replication():
    # tp_plan_llama replicates norm.weight with an explicit P(); a later
    # FSDP catch-all must NOT override it.
    plan = combine_plans(tp_plan_llama(), fsdp_plan(min_size=1))
    assert tuple(plan("model.norm.weight", (4096,))) == ()
    # Unmatched names fall through to the FSDP rule.
    assert plan("model.other.weight", (4096, 64)) == P("fsdp", None)


def test_fsdp_over_shards_free_dims():
    plan = fsdp_over(tp_plan_llama(), min_size=1)
    spec = plan("layers.0.q_proj.weight", (64, 64))
    assert spec == P("tp", "fsdp")
    # norm stays fully replicated (no free large dim under min_size rule
    # still shards 1-d? shape (64,) has a free dim -> fsdp over it)
    spec = plan("model.norm.weight", (64,))
    assert spec == P("fsdp")


def test_fit_spec_to_mesh_drops_absent_axes():
    mesh = make_mesh(MeshSpec(dp=8))
    assert fit_spec_to_mesh(P("fsdp", "tp"), mesh) == P(None, None)
    assert fit_spec_to_mesh(P(("dp", "fsdp"), None), mesh) == P("dp", None)


def test_replicate_indivisible():
    mesh = make_mesh(MeshSpec(tp=3), devices=jax.devices()[:3])
    assert replicate_indivisible(P("tp"), (9,), mesh) == P("tp")
    assert replicate_indivisible(P("tp"), (10,), mesh) == P(None)
    # shorter spec than rank is padded
    assert replicate_indivisible(P("tp"), (9, 5), mesh) == P("tp", None)
